"""Experiment configuration: the expconf analog, TPU-first.

The reference validates a versioned YAML "expconf" against JSON schemas
(``master/pkg/schemas/expconf``, ``schemas/expconf/v0/experiment.json``) with
cluster-side defaulting and merging.  Here the same contract is expressed as
typed dataclasses with explicit validation and ``merge``/defaulting, which is
both the schema and the parser (no codegen step).

Key TPU-first divergence: the reference's ``resources.slots_per_trial`` +
launcher choice (torch_distributed/horovod/deepspeed) collapses into a
``resources.mesh`` MeshConfig — the single declaration of dp/fsdp/tp/sp/ep/pp
topology (see ``determined_tpu/parallel/mesh.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import yaml

from determined_tpu.config.hyperparameters import parse_hyperparameters
from determined_tpu.parallel.mesh import MeshConfig


class InvalidExperimentConfig(ValueError):
    pass


#: quantized-matmul modes — the single source of truth shared with
#: ``train/_quant.py`` (which imports from here; no cycle)
QUANT_MODES = ("none", "int8", "fp8")

#: pipeline microbatch schedules — shared with ``parallel/pipeline.py``
#: (which imports from here; no cycle)
PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved")


_LENGTH_UNITS = ("batches", "epochs", "records")


@dataclasses.dataclass(frozen=True)
class Length:
    """Training length in batches/epochs/records — reference TrainUnit
    (``harness/determined/pytorch/_trainer_utils.py:9-151``)."""

    units: int
    unit: str = "batches"

    def __post_init__(self):
        if self.unit not in _LENGTH_UNITS:
            raise InvalidExperimentConfig(f"length unit {self.unit!r} not in {_LENGTH_UNITS}")
        if self.units < 0:
            raise InvalidExperimentConfig(f"length must be >= 0, got {self.units}")

    @classmethod
    def parse(cls, raw: Any, default_unit: str = "batches") -> "Length":
        if isinstance(raw, Length):
            return raw
        if isinstance(raw, int):
            return cls(raw, default_unit)
        if isinstance(raw, dict):
            if len(raw) != 1:
                raise InvalidExperimentConfig(f"length must have one key, got {raw}")
            (unit, units), = raw.items()
            return cls(int(units), unit)
        raise InvalidExperimentConfig(f"cannot parse length {raw!r}")

    @classmethod
    def batches(cls, n: int) -> "Length":
        return cls(n, "batches")

    @classmethod
    def epochs(cls, n: int) -> "Length":
        return cls(n, "epochs")

    @classmethod
    def records(cls, n: int) -> "Length":
        return cls(n, "records")


def clone_extended_length(max_length: Length, inherited_steps: int,
                          logger: Any = None, context: str = "") -> Length:
    """A clone-resumed trial's budget is ``max_length`` BEYOND the steps
    inherited from its source checkpoint: the trainer's step horizon is
    absolute and the restored state already carries the parent's count.
    One rule for both drivers (``experiment/local.py`` and the cluster
    harness's ``DTPU_WARM_START_STEPS`` path) so they cannot diverge.
    Only batch budgets extend; others stay absolute with a warning."""
    if not inherited_steps or inherited_steps <= 0:
        return max_length
    if max_length.unit != "batches":
        if logger is not None:
            logger.warning(
                "%sclone budget extension needs a batches max_length; "
                "%s budget left absolute", context, max_length.unit,
            )
        return max_length
    return Length.batches(max_length.units + int(inherited_steps))


@dataclasses.dataclass(frozen=True)
class SearcherConfig:
    """Searcher section — reference ``schemas/expconf/v0/searcher.json``.

    name: single | random | grid | asha | adaptive_asha | driver

    ``driver`` is execution-only: the search loop lives in a remote
    cluster-experiment driver (``experiment/cluster.py``), which submits
    each trial it creates to the master; a driver config never builds a
    local SearchMethod.
    """

    name: str = "single"
    metric: str = "validation_loss"
    smaller_is_better: bool = True
    max_trials: int = 1
    max_length: Optional[Length] = None          # per-trial budget
    max_concurrent_trials: int = 16
    # ASHA knobs (reference asha_stopping.go / adaptive_asha.go); divisor
    # doubles as hyperband's eta
    num_rungs: int = 5
    divisor: int = 4
    mode: str = "standard"                        # conservative|standard|aggressive
    max_time: Optional[int] = None                # asha/hyperband max units per trial
    time_metric: Optional[str] = None
    bracket_rungs: Optional[List[int]] = None
    source_trial_id: Optional[int] = None
    # PBT knobs (Jaderberg et al.; searcher/_pbt.py).  One generation's
    # training budget is max_length — the same per-trial budget knob every
    # other method uses.
    population_size: Optional[int] = None         # default: max_trials
    num_generations: int = 4
    truncate_fraction: float = 0.25
    perturb_factor: float = 1.2
    resample_probability: float = 0.25

    _NAMES = ("single", "random", "grid", "asha", "adaptive_asha",
              "hyperband", "pbt", "driver")

    def __post_init__(self):
        if self.name not in self._NAMES:
            raise InvalidExperimentConfig(f"unknown searcher {self.name!r}")
        if self.mode not in ("conservative", "standard", "aggressive"):
            raise InvalidExperimentConfig(f"unknown adaptive mode {self.mode!r}")
        if self.max_trials < 1:
            raise InvalidExperimentConfig("searcher.max_trials must be >= 1")
        if self.population_size is not None and self.population_size < 1:
            raise InvalidExperimentConfig("searcher.population_size must be >= 1")
        if self.num_generations < 1:
            raise InvalidExperimentConfig("searcher.num_generations must be >= 1")
        if not 0.0 <= self.truncate_fraction <= 0.5:
            raise InvalidExperimentConfig(
                "searcher.truncate_fraction must be in [0, 0.5]"
            )
        if self.perturb_factor <= 1.0:
            raise InvalidExperimentConfig("searcher.perturb_factor must be > 1")
        if not 0.0 <= self.resample_probability <= 1.0:
            raise InvalidExperimentConfig(
                "searcher.resample_probability must be in [0, 1]"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "SearcherConfig":
        raw = dict(raw or {})
        if "max_length" in raw and raw["max_length"] is not None:
            raw["max_length"] = Length.parse(raw["max_length"])
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown searcher fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic gang policy (``docs/cluster.md`` "Elastic gang training").

    The master may resize the trial's gang at runtime between a floor and
    the configured full size: slice/agent loss shrinks it (a capacity
    event — ``max_restarts`` is never spent), and stable returning
    capacity grows it back, slice-quantum aligned, through WAL-journaled
    checkpoint-restore-reshard transitions.  ``max_slots`` is the gang's
    full size — the wildcard mesh axis absorbs whatever width the master
    actually placed (``DTPU_ELASTIC_SLOTS``).  The floor is ``min_slots``
    (chips) or ``min_slices`` (topology slices, resolved against the live
    slice size at schedule time); ``resize_cooldown_s`` + a >= 1 slice
    minimum-gain gate stop a flapping agent from thrashing the trial
    through restore loops.  Requires a wildcard (-1) mesh axis so the
    restored mesh can absorb the new device count.
    """

    max_slots: int = 1
    min_slots: Optional[int] = None
    min_slices: Optional[int] = None
    resize_cooldown_s: int = 60

    def __post_init__(self):
        if self.max_slots < 1:
            raise InvalidExperimentConfig("elastic.max_slots must be >= 1")
        if self.min_slots is not None and self.min_slots > self.max_slots:
            raise InvalidExperimentConfig(
                f"elastic.min_slots={self.min_slots} exceeds "
                f"max_slots={self.max_slots}"
            )
        if self.min_slots is not None and self.min_slots < 1:
            raise InvalidExperimentConfig("elastic.min_slots must be >= 1")
        if self.min_slices is not None and self.min_slices < 1:
            raise InvalidExperimentConfig("elastic.min_slices must be >= 1")
        if self.resize_cooldown_s < 0:
            raise InvalidExperimentConfig(
                "elastic.resize_cooldown_s must be >= 0"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "ElasticConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(
                f"unknown elastic fields: {sorted(unknown)}"
            )
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class ResourcesConfig:
    """Resources — replaces reference ``slots_per_trial`` with a mesh.

    ``mesh`` axes multiply to the chip count of the trial; ``slots_per_trial``
    is still accepted as sugar for ``mesh: {data: N}``.
    """

    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    resource_pool: str = "default"
    priority: int = 42                            # reference default priority
    weight: float = 1.0                           # fair-share weight
    single_slice: bool = False                    # refuse DCN-spanning gang splits
    elastic: Optional[ElasticConfig] = None       # resizable-gang policy

    def __post_init__(self):
        if self.elastic is not None and -1 not in self.mesh.sizes():
            raise InvalidExperimentConfig(
                "resources.elastic requires a wildcard (-1) mesh axis: a "
                "resize changes the device count, and a fully pinned mesh "
                "cannot absorb it (e.g. mesh: {data: -1})"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "ResourcesConfig":
        raw = dict(raw or {})
        slots = raw.pop("slots_per_trial", None)
        elastic_raw = raw.pop("elastic", None)
        if elastic_raw is not None:
            raw["elastic"] = ElasticConfig.parse(elastic_raw)
        mesh_raw = raw.pop("mesh", None)
        if mesh_raw is not None and slots is not None:
            raise InvalidExperimentConfig(
                "resources.slots_per_trial and resources.mesh are mutually exclusive"
            )
        if mesh_raw is not None:
            try:
                mesh = MeshConfig(**mesh_raw)
            except TypeError:
                known_axes = [f.name for f in dataclasses.fields(MeshConfig)]
                raise InvalidExperimentConfig(
                    f"unknown mesh axes {sorted(set(mesh_raw) - set(known_axes))}; "
                    f"valid axes: {known_axes}"
                ) from None
        elif slots is not None:
            mesh = MeshConfig(data=int(slots))
        else:
            mesh = MeshConfig()
        known = {f.name for f in dataclasses.fields(cls)} - {"mesh"}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown resources fields: {sorted(unknown)}")
        return cls(mesh=mesh, **raw)

    @property
    def slots_per_trial(self) -> int:
        # elastic gangs size by their policy ceiling: the wildcard mesh
        # axis makes the axis product meaningless as a gang size
        if self.elastic is not None:
            return self.elastic.max_slots
        return self.mesh.num_devices


@dataclasses.dataclass(frozen=True)
class CheckpointStorageConfig:
    """Checkpoint storage — reference ``schemas/expconf/v0/checkpoint-storage.json``.

    type: shared_fs | directory | s3 | gcs | azure
    """

    type: str = "shared_fs"
    host_path: Optional[str] = None               # shared_fs
    storage_path: Optional[str] = None
    container_path: Optional[str] = None          # directory
    bucket: Optional[str] = None                  # s3/gcs
    prefix: Optional[str] = None
    save_experiment_best: int = 0
    save_trial_best: int = 1
    save_trial_latest: int = 1

    def to_url(self) -> str:
        if self.type in ("shared_fs", "directory"):
            base = self.host_path or self.container_path or "/tmp/determined_tpu/checkpoints"
            if self.storage_path:
                base = f"{base.rstrip('/')}/{self.storage_path}"
            return base
        if self.type in ("s3", "gcs"):
            if not self.bucket:
                raise InvalidExperimentConfig(f"{self.type} storage requires `bucket`")
            url = f"{self.type}://{self.bucket}"
            if self.prefix:
                url += f"/{self.prefix}"
            return url
        raise InvalidExperimentConfig(f"unknown checkpoint storage type {self.type!r}")

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "CheckpointStorageConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown checkpoint_storage fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class ReproducibilityConfig:
    experiment_seed: int = 0


@dataclasses.dataclass(frozen=True)
class OptimizationsConfig:
    """Gradient accumulation — reference ``optimizations.aggregation_frequency``
    (``_pytorch_context.py:708-914``).  Each optimizer step consumes
    ``aggregation_frequency`` microbatches of ``global_batch_size`` via an
    on-device ``lax.scan`` (no host round-trips between microbatches)."""

    aggregation_frequency: int = 1
    average_aggregated_gradients: bool = True
    # Overlapped checkpointing (on by default — a beat-the-reference item,
    # SURVEY §7(b)): array serialization runs on a background thread while
    # training continues; the collective finalize lands at the next save,
    # preemption, or exit.  False restores fully synchronous saves.
    async_checkpointing: bool = True
    # Overlapped input pipeline (docs/input-pipeline.md).  prefetch_depth:
    # how many host batches the background fetch worker may run ahead of
    # the trainer (0 = fetch synchronously on the main thread, the
    # reference DataLoader's num_workers=0 analog).  device_prefetch: how
    # many batches to hold on-device ahead of the step (2 = double
    # buffering; <=1 = synchronous host->device transfer).  fetch_workers:
    # thread-pool width for per-item map-style dataset reads (0 = the
    # sequential loop; irrelevant for InMemoryDataset's columnar gather).
    prefetch_depth: int = 2
    device_prefetch: int = 2
    fetch_workers: int = 0
    # Persistent XLA compilation cache directory (also DTPU_COMPILATION_CACHE
    # env): a supervised restart after a crash re-jits from disk instead of
    # paying the full compile.  None disables.
    compilation_cache_dir: Optional[str] = None
    # Cross-trial jit-reuse cache (train/_jit_cache.py): same-architecture
    # trials in one process share compiled train/eval steps instead of
    # re-tracing identical programs.  In-process complement of the
    # persistent cache above (which covers cross-process reuse).
    jit_cache: bool = True
    # Overlapped gradient synchronization (train/_overlap.py, docs/
    # performance.md): partition the grad pytree into size-bounded buckets
    # and stage each bucket's reduce-scatter at its production point in
    # the backward pass (custom_vjp markers + sharding constraints), with
    # the optimizer consuming SHARDED grads/state and params all-gathered
    # after the update — XLA's latency-hiding scheduler then interleaves
    # the collectives with remaining backward compute instead of exposing
    # one end-of-backward reduction.  Off by default; numerically
    # equivalent to the baseline reduction (tests pin allclose after N
    # steps).  overlap_bucket_mb bounds one bucket's payload.
    overlap_grad_sync: bool = False
    overlap_bucket_mb: int = 4
    # Hierarchical ICI/DCN collectives (train/_overlap.py, docs/
    # performance.md "Multi-slice"): on a multi-slice mesh
    # (resources.mesh.num_slices > 1) restructure each bucket's gradient
    # sync into reduce-scatter over the intra-slice ICI axes, cross-slice
    # all-reduce over ``dcn`` carrying only the 1/N_ici sharded fragment,
    # and a closing all-gather within the slice — instead of the flat
    # treatment that rings full-gradient payload across the slow DCN
    # links.  Requires overlap_grad_sync (it reshapes the bucket sync
    # shardings); inert on a single-slice mesh.
    hierarchical_collectives: bool = False
    # Quantized matmul arithmetic (train/_quant.py): route the
    # transformer's dense/attention projection matmuls through int8 (or
    # fp8 where the platform supports it) with per-channel dynamic
    # scaling.  Master weights and optimizer state stay fp32; backward
    # runs in full precision (straight-through).  fp8 on an unsupported
    # platform is rejected at trainer setup with InvalidExperimentConfig.
    quantized_matmul: str = "none"
    # Pipeline microbatch schedule on the ``pipe`` mesh axis
    # (parallel/pipeline.py, docs/performance.md "Pipeline schedules"):
    # ``gpipe`` is the plain M+P-1 drain; ``1f1b`` keeps the same bubble
    # but caps live activations at P microbatches instead of M (custom
    # combined fwd/bwd schedule — the memory headroom that buys larger M);
    # ``interleaved`` gives each pipe rank ``virtual_stages`` non-adjacent
    # layer chunks via a circular rotation, shrinking the bubble fraction
    # from (P-1)/(M+P-1) toward (P-1)/(V*M+P-1).  Inert when the mesh has
    # no pipe axis (except interleaved, which requires one).
    pipeline_schedule: str = "gpipe"
    virtual_stages: int = 1

    _QUANT_MODES = QUANT_MODES

    def __post_init__(self):
        if self.aggregation_frequency < 1:
            raise InvalidExperimentConfig(
                "optimizations.aggregation_frequency must be >= 1"
            )
        for knob in ("prefetch_depth", "device_prefetch", "fetch_workers"):
            if getattr(self, knob) < 0:
                raise InvalidExperimentConfig(f"optimizations.{knob} must be >= 0")
        if self.overlap_bucket_mb < 1:
            raise InvalidExperimentConfig(
                "optimizations.overlap_bucket_mb must be >= 1"
            )
        if self.quantized_matmul not in self._QUANT_MODES:
            raise InvalidExperimentConfig(
                f"optimizations.quantized_matmul {self.quantized_matmul!r} "
                f"not in {self._QUANT_MODES}"
            )
        if self.pipeline_schedule not in PIPELINE_SCHEDULES:
            raise InvalidExperimentConfig(
                f"optimizations.pipeline_schedule {self.pipeline_schedule!r} "
                f"not in {PIPELINE_SCHEDULES}"
            )
        if self.virtual_stages < 1:
            raise InvalidExperimentConfig(
                f"optimizations.virtual_stages must be >= 1 "
                f"(got {self.virtual_stages})"
            )
        if self.pipeline_schedule == "interleaved" and self.virtual_stages < 2:
            raise InvalidExperimentConfig(
                "optimizations.pipeline_schedule: interleaved needs "
                f"virtual_stages >= 2 (got {self.virtual_stages}); with one "
                "virtual stage it IS gpipe"
            )
        if self.pipeline_schedule != "interleaved" and self.virtual_stages != 1:
            raise InvalidExperimentConfig(
                f"optimizations.virtual_stages={self.virtual_stages} only "
                "applies to pipeline_schedule: interleaved "
                f"(got {self.pipeline_schedule!r})"
            )
        if self.hierarchical_collectives and not self.overlap_grad_sync:
            raise InvalidExperimentConfig(
                "optimizations.hierarchical_collectives requires "
                "overlap_grad_sync: true (the two-level sync is expressed "
                "through the bucketed sync shardings)"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "OptimizationsConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown optimizations fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class FaultToleranceConfig:
    """Supervised-restart + checkpoint-integrity knobs.

    ``max_restarts`` (top-level, reference expconf) bounds how many
    TRANSIENT failures the trial supervisor absorbs; these fields shape
    the behavior of each restart: exponential backoff (base * 2^restarts,
    capped, jittered so a gang's processes don't stampede the master) and
    whether resume requires a verified integrity manifest.
    """

    restart_backoff_base: float = 1.0     # seconds before the first restart
    restart_backoff_cap: float = 60.0     # ceiling on any single delay
    restart_backoff_jitter: float = 0.25  # +/- fraction applied to the delay
    verify_checkpoints: bool = True       # manifest-verify on resume
    heartbeat_failure_threshold: int = 5  # consecutive misses -> master_unreachable
    # Cluster-driver outage tolerance: how long a trial watcher retries
    # master connection failures / 5xx (capped exponential backoff, the
    # failure-streak pattern) before declaring the trial lost.  Sized to
    # ride out a master crash + restart + journal replay, not a real
    # outage — the master WAL makes restarts re-attachable, so watchers
    # that outwait the restart resume polling as if nothing happened.
    master_unreachable_grace_s: float = 120.0
    # Experiment-level crash recovery (docs/fault-tolerance.md, "Experiment
    # recovery & preemption"): write-ahead journal of searcher snapshots +
    # trial lifecycle under checkpoint_dir/experiment.journal, enabling
    # LocalExperiment.resume() after a driver crash/preemption.
    journal: bool = True
    journal_compact_interval: int = 64    # appends between compactions (0 = never)
    # Graceful preemption: SIGTERM/SIGINT flags every in-flight trial's
    # PreemptContext; the driver waits up to this long for trials to
    # checkpoint-and-exit before journaling final state and exiting
    # "preempted, resumable".
    preempt_drain_seconds: float = 300.0
    # Apply the checkpoint retention policy (exec/gc_checkpoints.py:
    # latest-per-trial + top-k best, parents of kept checkpoints protected)
    # at journal-compaction points.
    gc_on_compaction: bool = True

    def __post_init__(self):
        if self.restart_backoff_base < 0 or self.restart_backoff_cap < 0:
            raise InvalidExperimentConfig("fault_tolerance backoff values must be >= 0")
        if not (0 <= self.restart_backoff_jitter <= 1):
            raise InvalidExperimentConfig(
                "fault_tolerance.restart_backoff_jitter must be in [0, 1]"
            )
        if self.heartbeat_failure_threshold < 1:
            raise InvalidExperimentConfig(
                "fault_tolerance.heartbeat_failure_threshold must be >= 1"
            )
        if self.master_unreachable_grace_s < 0:
            raise InvalidExperimentConfig(
                "fault_tolerance.master_unreachable_grace_s must be >= 0"
            )
        if self.journal_compact_interval < 0:
            raise InvalidExperimentConfig(
                "fault_tolerance.journal_compact_interval must be >= 0"
            )
        if self.preempt_drain_seconds < 0:
            raise InvalidExperimentConfig(
                "fault_tolerance.preempt_drain_seconds must be >= 0"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "FaultToleranceConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown fault_tolerance fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class LintConfig:
    """Trial preflight analyzer knobs (``determined_tpu/lint``).

    ``preflight``: run the static AST pass over the trial class before any
    device is allocated (LocalExperiment; the trial supervisor does the
    same before building the Trainer).  Warn-only unless ``strict``, which
    fails the experiment on ANY finding — the cheap way to protect a
    search's TPU-hours from a host-syncing or retrace-prone trial.
    ``retrace_sentinel``: wrap the jitted step functions and warn when one
    logical step compiles more than once (guards the jit-reuse cache's
    throughput win).  ``thread_sentinel``: run the trial under the
    thread-leak checker (warn mode) so leaked prefetch/scheduler workers
    surface in logs.  ``collective_sentinel``: wrap the control-plane
    collective entry points with the collective-sequence sentinel — every
    rank digests its (op, payload-structure) sequence and the digests ride
    the collectives themselves, so a rank that takes a divergent code path
    raises a named ``CollectiveDivergenceError`` at the next exchange
    instead of hanging the gang to the 600 s collective timeout (must be
    on for EVERY rank of a gang or none; the ``DTPU_COLLECTIVE_SENTINEL``
    env is the launch-layer override).  ``suppress``: rule ids disabled
    for this experiment (the per-line ``# dtpu: lint-ok[rule]`` comment is
    preferred — it keeps the audit local).
    """

    preflight: bool = True
    strict: bool = False
    retrace_sentinel: bool = False
    thread_sentinel: bool = False
    collective_sentinel: bool = False
    suppress: List[str] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        # validate rule ids at parse time: a typo'd suppression silently
        # linting everything would defeat the audit
        from determined_tpu.lint.rules import all_rules

        suppress = self.suppress
        if suppress is None:  # YAML `suppress:` with no value
            suppress = []
            object.__setattr__(self, "suppress", suppress)
        if isinstance(suppress, str) or not isinstance(suppress, (list, tuple)):
            raise InvalidExperimentConfig(
                f"lint.suppress must be a list of rule ids, got {suppress!r}"
            )
        unknown = set(suppress) - set(all_rules())
        if unknown:
            raise InvalidExperimentConfig(
                f"lint.suppress names unknown rules: {sorted(unknown)}"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "LintConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown lint fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Experiment-wide tracing knobs (``determined_tpu/observability``).

    ``enabled``: record spans/counters from every subsystem (trainer loop,
    prefetch workers, scheduler, journal, checkpoint writers, restarts)
    into per-thread ring buffers — lock-free, non-blocking, <2% step-time
    overhead (the DTPU_BENCH_TRACE A/B).  ``trace_export``: additionally
    stream the events as Chrome trace JSON under
    ``checkpoint_dir/traces/`` (Perfetto-loadable; feeds
    ``dtpu experiment profile``).  ``ring_capacity``: events buffered per
    thread between shipper drains — overflow drops (counted) rather than
    blocking.  ``flush_interval_s``: shipper drain cadence.
    ``max_events``: in-memory event cap for the end-of-run ledger.
    """

    enabled: bool = True
    trace_export: bool = False
    ring_capacity: int = 8192
    flush_interval_s: float = 0.5
    max_events: int = 1_000_000

    def __post_init__(self):
        if self.ring_capacity < 16:
            raise InvalidExperimentConfig(
                "observability.ring_capacity must be >= 16"
            )
        if self.flush_interval_s <= 0:
            raise InvalidExperimentConfig(
                "observability.flush_interval_s must be > 0"
            )
        if self.max_events < 1:
            raise InvalidExperimentConfig("observability.max_events must be >= 1")

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "ObservabilityConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(
                f"unknown observability fields: {sorted(unknown)}"
            )
        return cls(**raw)


_LOG_POLICY_ACTIONS = ("cancel_retries", "exclude_node")


@dataclasses.dataclass(frozen=True)
class LogPolicy:
    """Regex monitor on task logs — reference ``logpattern.go:27-247`` and
    ``expconf log_policies``.  ``cancel_retries``: a later trial failure is
    terminal (no restarts); ``exclude_node``: restarts avoid the agent whose
    logs matched."""

    pattern: str
    action: str
    name: Optional[str] = None

    def __post_init__(self):
        if not self.pattern:
            raise InvalidExperimentConfig("log_policies entries require a `pattern`")
        if self.action not in _LOG_POLICY_ACTIONS:
            raise InvalidExperimentConfig(
                f"log_policies action {self.action!r} not in {_LOG_POLICY_ACTIONS}"
            )
        import re

        try:
            re.compile(self.pattern)
        except re.error as e:
            raise InvalidExperimentConfig(
                f"log_policies pattern {self.pattern!r} is not a valid regex: {e}"
            ) from None

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "LogPolicy":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown log_policies fields: {sorted(unknown)}")
        return cls(**raw)


@dataclasses.dataclass(frozen=True)
class RegistryConfig:
    """Model-registry promotion (``docs/registry.md``).

    ``model``: the registry model name this experiment promotes into.
    ``auto_promote``: when the search completes, register the best trial's
    final manifest-verified checkpoint as the model's next version
    (``name@vN``) with lineage back to the trial and experiment — the
    driver's ``on_search_complete`` hook does the registration, so an
    ASHA/PBT search ends with its winner in the registry, ready for
    ``dtpu serve --model name@latest`` and a rolling deploy.  ``labels``
    ride on every version this experiment registers.  A registered
    version's checkpoint is pinned against checkpoint GC (both the
    driver's retention pass and the master's best-k rotation).
    """

    model: Optional[str] = None
    auto_promote: bool = False
    labels: List[str] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if self.auto_promote and not self.model:
            raise InvalidExperimentConfig(
                "registry.auto_promote requires registry.model"
            )
        if self.model is not None:
            if not isinstance(self.model, str) or not self.model:
                raise InvalidExperimentConfig("registry.model must be a string")
            # "@" is the name/version separator in model refs; whitespace
            # and "/" would break the CLI and the master's routes
            bad = set("@/ \t\n")
            if set(self.model) & bad:
                raise InvalidExperimentConfig(
                    f"registry.model {self.model!r} may not contain "
                    "'@', '/', or whitespace"
                )
        if isinstance(self.labels, str) or not isinstance(self.labels, (list, tuple)):
            raise InvalidExperimentConfig(
                f"registry.labels must be a list, got {self.labels!r}"
            )

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "RegistryConfig":
        raw = dict(raw or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise InvalidExperimentConfig(f"unknown registry fields: {sorted(unknown)}")
        return cls(**raw)


_CHECKPOINT_POLICIES = ("best", "all", "none")


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """Top-level experiment config — reference ``expconf/v0/experiment.json``."""

    name: str = "unnamed"
    entrypoint: Optional[str] = None
    description: str = ""
    labels: List[str] = dataclasses.field(default_factory=list)
    workspace: str = "Uncategorized"
    project: str = "Uncategorized"
    hyperparameters: Dict[str, Any] = dataclasses.field(default_factory=dict)
    searcher: SearcherConfig = dataclasses.field(default_factory=SearcherConfig)
    resources: ResourcesConfig = dataclasses.field(default_factory=ResourcesConfig)
    checkpoint_storage: CheckpointStorageConfig = dataclasses.field(
        default_factory=CheckpointStorageConfig
    )
    checkpoint_policy: str = "best"
    min_validation_period: Optional[Length] = None
    min_checkpoint_period: Optional[Length] = None
    records_per_epoch: int = 0
    max_restarts: int = 5
    fault_tolerance: FaultToleranceConfig = dataclasses.field(
        default_factory=FaultToleranceConfig
    )
    lint: LintConfig = dataclasses.field(default_factory=LintConfig)
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig
    )
    reproducibility: ReproducibilityConfig = dataclasses.field(
        default_factory=ReproducibilityConfig
    )
    optimizations: OptimizationsConfig = dataclasses.field(
        default_factory=OptimizationsConfig
    )
    registry: RegistryConfig = dataclasses.field(default_factory=RegistryConfig)
    environment: Dict[str, Any] = dataclasses.field(default_factory=dict)
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    profiling: Dict[str, Any] = dataclasses.field(default_factory=dict)
    log_policies: List[LogPolicy] = dataclasses.field(default_factory=list)
    unmanaged: bool = False
    raw: Dict[str, Any] = dataclasses.field(default_factory=dict, compare=False)

    def __post_init__(self):
        if self.checkpoint_policy not in _CHECKPOINT_POLICIES:
            raise InvalidExperimentConfig(
                f"checkpoint_policy {self.checkpoint_policy!r} not in {_CHECKPOINT_POLICIES}"
            )
        if self.searcher.name == "grid":
            # a grid over a continuous axis without `count` would silently
            # collapse to one point; reject at parse time (master re-checks
            # at submit: master.cpp validate_config)
            from determined_tpu.config.hyperparameters import Double, Log

            def walk(hp: Any, path: str) -> None:
                if isinstance(hp, dict):
                    for k, v in hp.items():
                        walk(v, f"{path}.{k}" if path else str(k))
                elif isinstance(hp, (Double, Log)) and hp.count is None:
                    raise InvalidExperimentConfig(
                        f"grid search over continuous hyperparameter {path!r} "
                        "requires an explicit `count`"
                    )

            walk(self.hyperparameters, "")

    @classmethod
    def parse(cls, raw: Dict[str, Any]) -> "ExperimentConfig":
        raw = dict(raw or {})
        # schema versioning (reference: versioned expconf union types):
        # v1 is the only version; an explicit other value is a config from
        # a different era and must fail loudly, not half-parse
        version = raw.pop("version", 1)
        if not (
            isinstance(version, (int, float))
            and not isinstance(version, bool)  # YAML true would == 1
            and version == 1
        ):
            raise InvalidExperimentConfig(
                f"unsupported experiment config version {version!r} (supported: 1)"
            )
        kwargs: Dict[str, Any] = {"raw": dict(raw)}
        if "hyperparameters" in raw:
            kwargs["hyperparameters"] = parse_hyperparameters(raw.pop("hyperparameters"))
        if "searcher" in raw:
            kwargs["searcher"] = SearcherConfig.parse(raw.pop("searcher"))
        if "resources" in raw:
            kwargs["resources"] = ResourcesConfig.parse(raw.pop("resources"))
        if "checkpoint_storage" in raw:
            kwargs["checkpoint_storage"] = CheckpointStorageConfig.parse(
                raw.pop("checkpoint_storage")
            )
        if "reproducibility" in raw:
            kwargs["reproducibility"] = ReproducibilityConfig(**raw.pop("reproducibility"))
        if "optimizations" in raw:
            kwargs["optimizations"] = OptimizationsConfig.parse(raw.pop("optimizations"))
        if "fault_tolerance" in raw:
            kwargs["fault_tolerance"] = FaultToleranceConfig.parse(raw.pop("fault_tolerance"))
        if "registry" in raw:
            kwargs["registry"] = RegistryConfig.parse(raw.pop("registry"))
        if "lint" in raw:
            kwargs["lint"] = LintConfig.parse(raw.pop("lint"))
        if "observability" in raw:
            kwargs["observability"] = ObservabilityConfig.parse(
                raw.pop("observability")
            )
        if "log_policies" in raw:
            policies = raw.pop("log_policies") or []
            if not isinstance(policies, list):
                raise InvalidExperimentConfig("log_policies must be a list")
            kwargs["log_policies"] = [LogPolicy.parse(p) for p in policies]
        for period in ("min_validation_period", "min_checkpoint_period"):
            if raw.get(period) is not None:
                kwargs[period] = Length.parse(raw.pop(period))
        known = {f.name for f in dataclasses.fields(cls)}
        for k in list(raw):
            if k in known and k != "raw":
                kwargs[k] = raw.pop(k)
        if raw:
            raise InvalidExperimentConfig(f"unknown experiment config fields: {sorted(raw)}")
        return cls(**kwargs)

    @classmethod
    def from_yaml(cls, path: str) -> "ExperimentConfig":
        with open(path) as f:
            return cls.parse(yaml.safe_load(f) or {})

    @classmethod
    def from_yaml_str(cls, text: str) -> "ExperimentConfig":
        return cls.parse(yaml.safe_load(text) or {})

    def with_hyperparameters(self, hparams: Dict[str, Any]) -> "ExperimentConfig":
        """A copy whose hp space is collapsed to concrete Const values
        (what a trial sees after the searcher samples)."""
        const = parse_hyperparameters(hparams)
        return dataclasses.replace(self, hyperparameters=const)


def preflight_experiment_config(cfg: "ExperimentConfig") -> List[str]:
    """Cross-field preflight checks surfaced by ``dtpu lint --config`` —
    the class of mistake single-field ``__post_init__`` validation cannot
    see (a knob valid on its own but wrong against the mesh or the
    hyperparameters) and that otherwise raises at trainer setup or, worse,
    at the first step.  Returns human-readable problem strings; empty
    means clean.  Only concrete (Const/int) hyperparameters participate —
    a searched hparam cannot be checked until the searcher samples it.
    """
    problems: List[str] = []
    opt = cfg.optimizations
    mesh = cfg.resources.mesh
    pipe = getattr(mesh, "pipe", 1)

    def hp_int(name: str) -> Optional[int]:
        v = cfg.hyperparameters.get(name)
        v = getattr(v, "val", v)
        return v if isinstance(v, int) and not isinstance(v, bool) else None

    if opt.pipeline_schedule == "interleaved" and 0 <= pipe <= 1:
        problems.append(
            "optimizations.pipeline_schedule: interleaved needs a "
            f"resources.mesh pipe axis > 1 (mesh pipe={pipe})"
        )
    if pipe > 1:
        chunks = pipe * opt.virtual_stages
        n_layers = hp_int("n_layers")
        if n_layers is not None and n_layers % chunks:
            problems.append(
                f"hyperparameters.n_layers={n_layers} does not divide into "
                f"{chunks} pipeline chunks (pipe={pipe} x "
                f"virtual_stages={opt.virtual_stages}) for "
                f"pipeline_schedule {opt.pipeline_schedule!r}"
            )
        gbs = hp_int("global_batch_size")
        m = hp_int("pipe_microbatches")
        if gbs is not None and m is not None and m > 0 and gbs % m:
            problems.append(
                f"hyperparameters.global_batch_size={gbs} not divisible by "
                f"pipe_microbatches={m}: the pipeline schedule would reject "
                "it at the first step"
            )
    return problems


def merge_configs(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge, override wins — reference ``schemas.Merge``
    (``master/pkg/schemas/merge.go``) semantics for template application."""
    out = dict(base)
    for k, v in override.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_configs(out[k], v)
        else:
            out[k] = v
    return out
