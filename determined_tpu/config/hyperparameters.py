"""Hyperparameter search space: typed HP declarations, sampling, grid expansion.

Reference semantics: ``master/pkg/searcher/hyperparameters.go`` (sampling of
const/int/double/log/categorical) and ``master/pkg/searcher/grid.go``
(cartesian grid expansion with ``count`` per axis).  Nested dicts of
hyperparameters are supported, as in the reference's expconf
(``schemas/expconf/v0/hyperparameters.json``).

YAML form mirrors the reference::

    hyperparameters:
      lr:
        type: log
        minval: -5
        maxval: -1
        base: 10
      hidden:
        type: int
        minval: 32
        maxval: 512
      act:
        type: categorical
        vals: [relu, gelu]
      layers: 4            # bare value == const
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class InvalidHyperparameter(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Const:
    val: Any

    def sample(self, rng: np.random.Generator) -> Any:
        return self.val

    def grid(self) -> List[Any]:
        return [self.val]


@dataclasses.dataclass(frozen=True)
class Int:
    minval: int
    maxval: int
    count: Optional[int] = None  # grid points

    def __post_init__(self):
        if self.minval > self.maxval:
            raise InvalidHyperparameter(f"int hp minval {self.minval} > maxval {self.maxval}")
        if self.count is not None and self.count < 1:
            raise InvalidHyperparameter(f"int hp count must be >= 1, got {self.count}")

    def sample(self, rng: np.random.Generator) -> int:
        return int(rng.integers(self.minval, self.maxval + 1))

    def grid(self) -> List[int]:
        # Reference grid.go caps count at the number of distinct ints.
        span = self.maxval - self.minval + 1
        count = min(self.count or span, span)
        if count == 1:
            return [self.minval]
        step = (self.maxval - self.minval) / (count - 1)
        return sorted({int(round(self.minval + i * step)) for i in range(count)})


@dataclasses.dataclass(frozen=True)
class Double:
    minval: float
    maxval: float
    count: Optional[int] = None

    def __post_init__(self):
        if self.minval > self.maxval:
            raise InvalidHyperparameter(f"double hp minval {self.minval} > maxval {self.maxval}")
        if self.count is not None and self.count < 1:
            raise InvalidHyperparameter(f"double hp count must be >= 1, got {self.count}")

    def sample(self, rng: np.random.Generator) -> float:
        return float(rng.uniform(self.minval, self.maxval))

    def grid(self) -> List[float]:
        if self.count is None:
            raise InvalidHyperparameter("grid search requires `count` on double hps")
        if self.count == 1:
            return [self.minval]
        step = (self.maxval - self.minval) / (self.count - 1)
        return [self.minval + i * step for i in range(self.count)]


@dataclasses.dataclass(frozen=True)
class Log:
    """Sampled as base**u for u ~ U(minval, maxval) — reference Log HP."""

    minval: float
    maxval: float
    base: float = 10.0
    count: Optional[int] = None

    def __post_init__(self):
        if self.minval > self.maxval:
            raise InvalidHyperparameter(f"log hp minval {self.minval} > maxval {self.maxval}")
        if self.count is not None and self.count < 1:
            raise InvalidHyperparameter(f"log hp count must be >= 1, got {self.count}")

    def sample(self, rng: np.random.Generator) -> float:
        return float(self.base ** rng.uniform(self.minval, self.maxval))

    def grid(self) -> List[float]:
        if self.count is None:
            raise InvalidHyperparameter("grid search requires `count` on log hps")
        if self.count == 1:
            return [self.base ** self.minval]
        step = (self.maxval - self.minval) / (self.count - 1)
        return [self.base ** (self.minval + i * step) for i in range(self.count)]


@dataclasses.dataclass(frozen=True)
class Categorical:
    vals: Sequence[Any]

    def __post_init__(self):
        if not self.vals:
            raise InvalidHyperparameter("categorical hp needs at least one value")

    def sample(self, rng: np.random.Generator) -> Any:
        return self.vals[int(rng.integers(0, len(self.vals)))]

    def grid(self) -> List[Any]:
        return list(self.vals)


Hyperparameter = Any  # Const | Int | Double | Log | Categorical


def parse_hyperparameter(raw: Any) -> Hyperparameter:
    """Parse one YAML hp declaration. Bare scalars/lists become Const."""
    if isinstance(raw, dict) and "type" in raw:
        t = raw["type"]
        if t == "const":
            return Const(raw["val"])
        if t == "int":
            return Int(int(raw["minval"]), int(raw["maxval"]), raw.get("count"))
        if t == "double":
            return Double(float(raw["minval"]), float(raw["maxval"]), raw.get("count"))
        if t == "log":
            return Log(
                float(raw["minval"]),
                float(raw["maxval"]),
                float(raw.get("base", 10.0)),
                raw.get("count"),
            )
        if t == "categorical":
            return Categorical(tuple(raw["vals"]))
        raise InvalidHyperparameter(f"unknown hyperparameter type {t!r}")
    return Const(raw)


def parse_hyperparameters(raw: Dict[str, Any]) -> Dict[str, Any]:
    """Parse a (possibly nested) dict of hp declarations."""
    out: Dict[str, Any] = {}
    for k, v in (raw or {}).items():
        if isinstance(v, dict) and "type" not in v:
            out[k] = parse_hyperparameters(v)
        else:
            out[k] = parse_hyperparameter(v)
    return out


def _walk(space: Dict[str, Any], prefix=()) -> Iterator:
    for k, v in space.items():
        if isinstance(v, dict):
            yield from _walk(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def _set_nested(d: Dict[str, Any], path, val) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = val


def sample_hyperparameters(space: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
    """Draw one concrete hp dict from the space (random search / ASHA)."""
    out: Dict[str, Any] = {}
    for path, hp in _walk(space):
        _set_nested(out, path, hp.sample(rng))
    return out


def grid_points(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product of per-hp grids — reference ``grid.go`` semantics."""
    paths: List = []
    axes: List[List[Any]] = []
    for path, hp in _walk(space):
        paths.append(path)
        axes.append(hp.grid())
    points = []
    for combo in itertools.product(*axes) if axes else [()]:
        d: Dict[str, Any] = {}
        for path, val in zip(paths, combo):
            _set_nested(d, path, val)
        points.append(d)
    return points


def grid_size(space: Dict[str, Any]) -> int:
    return int(math.prod(len(hp.grid()) for _, hp in _walk(space)) if space else 1)
