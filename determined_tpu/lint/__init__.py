"""Trial preflight analyzer: static JAX-footgun lint + runtime sentinels.

The reference platform keeps its master/agent concurrency honest with Go's
race detector and vet passes; the harness side here has no analog, yet it
is deeply concurrent (prefetch workers, per-trial scheduler threads,
background checkpoint writers) and its scheduler's throughput depends on
trial code that neither retraces nor syncs the host mid-step.  This
package vets trial code BEFORE devices are allocated:

- static pass (``_ast.py`` + ``rules/``): AST analysis of a JaxTrial
  subclass or a source tree, typed diagnostics with rule ids and
  ``file:line`` anchors, ``# dtpu: lint-ok[rule]`` suppressions;
- runtime sentinels (``_runtime.py``): a retrace detector wrapping the
  jitted step functions, and a thread-leak checker for tests and the trial
  supervisor.

Surfaces: ``dtpu lint <path|module:Class>`` (``cli/main.py``),
``LocalExperiment`` preflight (warn by default, ``lint.strict`` fails
fast), ``scripts/lint.sh`` in CI.  Rule catalog: ``docs/lint.md``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from determined_tpu.lint._ast import (
    analyze_class,
    analyze_entrypoint,
    analyze_file,
    analyze_path,
    analyze_paths,
    analyze_source,
)
from determined_tpu.lint._diag import (
    ERROR,
    SCHEMA_VERSION,
    WARNING,
    Diagnostic,
    LintError,
    to_json_payload,
)
from determined_tpu.lint._native import (
    NativeIndex,
    NativeSources,
    build_native_index,
    collect_native_sources,
    find_native_root,
    lint_native,
    run_native_pass,
)
from determined_tpu.lint._runtime import (
    CollectiveDivergenceError,
    CollectiveSequenceSentinel,
    LockOrderSentinel,
    LockOrderViolation,
    RetraceSentinel,
    ThreadLeakChecker,
    ThreadLeakError,
    get_collective_sentinel,
    get_retrace_sentinel,
)
from determined_tpu.lint.rules import all_rules


def check_trial(
    trial_cls: type,
    *,
    disabled: Optional[Sequence[str]] = None,
) -> List[Diagnostic]:
    """Preflight a trial class; unavailable source yields zero findings
    (warn-mode callers log; strict callers still pass vacuously rather
    than rejecting code the analyzer simply cannot read)."""
    try:
        return analyze_class(trial_cls, disabled=disabled)
    except (OSError, TypeError):
        return []


__all__ = [
    "CollectiveDivergenceError",
    "CollectiveSequenceSentinel",
    "Diagnostic",
    "ERROR",
    "LintError",
    "LockOrderSentinel",
    "LockOrderViolation",
    "NativeIndex",
    "NativeSources",
    "RetraceSentinel",
    "SCHEMA_VERSION",
    "ThreadLeakChecker",
    "ThreadLeakError",
    "WARNING",
    "all_rules",
    "analyze_class",
    "analyze_entrypoint",
    "analyze_file",
    "analyze_path",
    "analyze_paths",
    "analyze_source",
    "build_native_index",
    "check_trial",
    "collect_native_sources",
    "find_native_root",
    "get_collective_sentinel",
    "lint_native",
    "run_native_pass",
    "get_retrace_sentinel",
    "to_json_payload",
]
