"""Typed diagnostics for the trial preflight analyzer.

A ``Diagnostic`` is one finding: rule id, severity, message, and the
``file:line:col`` anchor.  The same record feeds the CLI's text and JSON
output, the preflight warn-log, and ``LintError`` (the strict-mode
failure), so every surface agrees on what was found.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence

ERROR = "error"
WARNING = "warning"
SEVERITIES = (ERROR, WARNING)

#: JSON output schema version (bump on breaking field changes)
SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One lint finding, anchored to source."""

    rule: str
    severity: str
    message: str
    file: str
    line: int
    col: int = 0

    def format(self) -> str:
        return (
            f"{self.file}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def to_json_payload(diagnostics: Sequence[Diagnostic]) -> Dict[str, Any]:
    """The ``dtpu lint --json`` document: versioned, with per-severity and
    per-rule counts so CI can gate without re-aggregating."""
    by_rule: Dict[str, int] = {}
    by_severity: Dict[str, int] = {s: 0 for s in SEVERITIES}
    for d in diagnostics:
        by_rule[d.rule] = by_rule.get(d.rule, 0) + 1
        by_severity[d.severity] = by_severity.get(d.severity, 0) + 1
    return {
        "version": SCHEMA_VERSION,
        "findings": [d.to_dict() for d in diagnostics],
        "counts": {"total": len(diagnostics), "by_severity": by_severity, "by_rule": by_rule},
    }


class LintError(Exception):
    """Strict preflight failure: carries the diagnostics that caused it."""

    def __init__(self, diagnostics: Sequence[Diagnostic], context: str = "") -> None:
        self.diagnostics: List[Diagnostic] = list(diagnostics)
        lines = [d.format() for d in self.diagnostics]
        head = context or f"{len(lines)} lint finding(s)"
        super().__init__(head + ("\n" + "\n".join(lines) if lines else ""))
