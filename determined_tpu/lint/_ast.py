"""AST core of the trial preflight analyzer.

Design: one walk per module with a scope-tracking visitor; rules are
stateless-ish objects dispatched per node (``rules/__init__.py``).  The
walker computes the two pieces of context every rule needs:

- **step context** — whether the current code is traced by XLA.  A
  ``JaxTrial`` subclass's ``loss``/``evaluate_batch``/``init_params``
  methods are traced (the Trainer jits them), as is any function carrying a
  ``jax.jit``-style decorator or named ``train_step``/``eval_step`` (the
  Trainer's own convention); nested functions inherit the property.
- **traced names** — which local names hold traced values inside a step
  function: its parameters (minus ``self``/``model``) seeded, then a cheap
  two-pass forward taint (``x = f(batch)`` makes ``x`` traced).  Attribute
  reads of static metadata (``.shape``/``.dtype``/``.ndim``) break the
  taint, so shape-based Python branching stays legal.

Trial classes are detected structurally — a base name whose last segment
ends in ``Trial`` — so the analyzer works on source that cannot be
imported; ``analyze_class`` (an imported class object) force-marks the
class instead.

Suppressions: ``# dtpu: lint-ok[rule-a,rule-b]`` (or bare ``lint-ok`` for
all rules) on the finding's line, or alone on the line above it.
"""

from __future__ import annotations

import ast
import io
import os
import re
import textwrap
import tokenize
from typing import Any, Dict, List, Optional, Sequence, Set

from determined_tpu.lint._diag import ERROR, WARNING, Diagnostic

#: JaxTrial methods the Trainer traces under jit
STEP_METHODS = frozenset({"loss", "evaluate_batch", "init_params"})
#: function names treated as traced step bodies anywhere (Trainer idiom)
STEP_FUNCTION_NAMES = frozenset({"train_step", "eval_step"})
#: parameters of step methods that are NOT traced values
UNTRACED_PARAMS = frozenset({"self", "cls", "model"})
#: attribute reads that yield static (host) metadata of a traced array
STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding", "aval"})

_SUPPRESS_RE = re.compile(r"#\s*dtpu:\s*lint-ok(?:\[([^\]]*)\])?")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def parse_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule ids (None = all rules).

    A comment alone on its line also covers the next line, so findings can
    be suppressed above the statement they refer to.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = (
                {r.strip() for r in m.group(1).split(",") if r.strip()}
                if m.group(1) is not None
                else None
            )
            line = tok.start[0]
            targets = [line]
            text_before = lines[line - 1][: tok.start[1]] if line <= len(lines) else ""
            if not text_before.strip():
                targets.append(line + 1)
            for t in targets:
                prev = out.get(t, set())
                if prev is None or rules is None:
                    out[t] = None
                else:
                    out[t] = prev | rules
    except tokenize.TokenError:
        pass
    return out


class FunctionScope:
    """One function on the walker's stack."""

    def __init__(self, node: ast.AST, is_step: bool, traced: Set[str]) -> None:
        self.node = node
        self.name = getattr(node, "name", "<lambda>")
        self.is_step = is_step
        self.traced = traced


class ClassScope:
    def __init__(self, node: ast.ClassDef, is_trial: bool) -> None:
        self.node = node
        self.name = node.name
        self.is_trial = is_trial


class LintContext:
    """What rules see: scope stacks, taint info, and the report sink."""

    def __init__(
        self,
        filename: str,
        source: str,
        *,
        line_offset: int = 0,
        assume_trial_classes: Optional[Set[str]] = None,
    ) -> None:
        self.filename = filename
        self.source = source
        self.line_offset = line_offset
        self.assume_trial_classes = assume_trial_classes or set()
        self.suppressions = parse_suppressions(source)
        self.diagnostics: List[Diagnostic] = []
        self.class_stack: List[ClassScope] = []
        self.func_stack: List[FunctionScope] = []
        #: ids of Call nodes that are bare expression statements (their
        #: value is discarded — the call exists for its side effect)
        self.stmt_calls: Set[int] = set()

    # -- scope queries -----------------------------------------------------

    @property
    def in_step(self) -> bool:
        return any(f.is_step for f in self.func_stack)

    @property
    def current_class(self) -> Optional[ClassScope]:
        return self.class_stack[-1] if self.class_stack else None

    @property
    def in_trial_class(self) -> bool:
        return any(c.is_trial for c in self.class_stack)

    def traced_names(self) -> Set[str]:
        """Union of traced names over the enclosing step functions (a
        nested helper inside ``loss`` sees the outer taint too)."""
        out: Set[str] = set()
        for f in self.func_stack:
            if f.is_step:
                out |= f.traced
        return out

    # -- reporting ---------------------------------------------------------

    def report(
        self,
        rule: Any,
        node: ast.AST,
        message: str,
        *,
        severity: Optional[str] = None,
    ) -> None:
        line = getattr(node, "lineno", 1)
        sup = self.suppressions.get(line)
        if sup is None and line in self.suppressions:
            return  # bare lint-ok: everything suppressed
        if sup is not None and rule.id in sup:
            return
        self.diagnostics.append(
            Diagnostic(
                rule=rule.id,
                severity=severity or rule.severity,
                message=message,
                file=self.filename,
                line=line + self.line_offset,
                col=getattr(node, "col_offset", 0),
            )
        )


def references_traced_value(node: ast.AST, traced: Set[str]) -> bool:
    """Does this expression's VALUE depend on a traced array (as opposed to
    static metadata like ``.shape``)?"""
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return references_traced_value(node.value, traced)
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "len":
            return False  # len() of an array is its leading shape dim
        if isinstance(fn, ast.Attribute) and fn.attr in ("items", "keys", "values"):
            # structure iteration over a pytree container is static
            return False
        return any(
            references_traced_value(c, traced) for c in ast.iter_child_nodes(node)
        )
    return any(references_traced_value(c, traced) for c in ast.iter_child_nodes(node))


def local_names(fn_node: ast.AST) -> Set[str]:
    """Names bound in this function: params, plain Name stores, nested
    defs — EXCLUDING names declared ``global``/``nonlocal`` (stores to
    those rebind an OUTER scope, so they are shared, not local).  Shared
    by the side-effect and concurrency rules; nested functions' bindings
    count toward the enclosing function (a deliberate coarse-grain)."""
    declared_outer: Set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.Global, ast.Nonlocal)):
            declared_outer.update(sub.names)
    out: Set[str] = set()
    args = getattr(fn_node, "args", None)
    if args is not None:
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            out.add(a.arg)
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                out.add(extra.arg)
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
        elif (
            isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
            and sub is not fn_node
        ):
            out.add(sub.name)
    return out - declared_outer


def _assigned_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
    return out


def _taint_function(node: ast.AST, seed: Set[str]) -> Set[str]:
    """Two forward passes of name-level taint over the function body."""
    traced = set(seed)
    body = getattr(node, "body", [])
    for _ in range(2):
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Assign) and references_traced_value(
                    sub.value, traced
                ):
                    for t in sub.targets:
                        traced |= _assigned_names(t)
                elif isinstance(sub, ast.AugAssign) and references_traced_value(
                    sub.value, traced
                ):
                    traced |= _assigned_names(sub.target)
    return traced


def _has_jit_decorator(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        names: List[Optional[str]] = [dotted_name(dec)]
        if isinstance(dec, ast.Call):
            names.append(dotted_name(dec.func))
            names.extend(dotted_name(a) for a in dec.args)
        for name in names:
            if name and (name == "jit" or name.endswith(".jit")):
                return True
    return False


def _is_trial_classdef(node: ast.ClassDef, assume: Set[str]) -> bool:
    if node.name in assume:
        return True
    for base in node.bases:
        name = dotted_name(base)
        if name and name.split(".")[-1].endswith("Trial"):
            return True
    return False


class _Walker(ast.NodeVisitor):
    def __init__(self, ctx: LintContext, rules: Sequence[Any]) -> None:
        self.ctx = ctx
        self.rules = rules

    def _dispatch(self, hook: str, node: ast.AST) -> None:
        for rule in self.rules:
            fn = getattr(rule, hook, None)
            if fn is not None:
                fn(node, self.ctx)

    # -- scopes ------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        scope = ClassScope(
            node, _is_trial_classdef(node, self.ctx.assume_trial_classes)
        )
        self.ctx.class_stack.append(scope)
        self._dispatch("visit_classdef", node)
        self.generic_visit(node)
        self.ctx.class_stack.pop()

    def _visit_function(self, node: ast.AST) -> None:
        ctx = self.ctx
        name = getattr(node, "name", "<lambda>")
        in_trial_method = (
            ctx.current_class is not None
            and ctx.current_class.is_trial
            and not ctx.func_stack
        )
        is_step = (
            (in_trial_method and name in STEP_METHODS)
            or name in STEP_FUNCTION_NAMES
            or _has_jit_decorator(node)
            or ctx.in_step  # nested in a step function
        )
        traced: Set[str] = set()
        if is_step:
            args = getattr(node, "args", None)
            if args is not None:
                params = [
                    a.arg
                    for a in (
                        list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
                    )
                ]
                for extra in (args.vararg, args.kwarg):
                    if extra is not None:
                        params.append(extra.arg)
                traced = {p for p in params if p not in UNTRACED_PARAMS}
            traced |= ctx.traced_names()
            traced = _taint_function(node, traced)
        ctx.func_stack.append(FunctionScope(node, is_step, traced))
        self._dispatch("visit_functiondef", node)
        self.generic_visit(node)
        ctx.func_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambdas inherit step context but add no scope bookkeeping
        self.generic_visit(node)

    # -- dispatched nodes ----------------------------------------------------

    def visit_Expr(self, node: ast.Expr) -> None:
        if isinstance(node.value, ast.Call):
            self.ctx.stmt_calls.add(id(node.value))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._dispatch("visit_call", node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._dispatch("visit_assign", node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._dispatch("visit_augassign", node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._dispatch("visit_if", node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._dispatch("visit_while", node)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._dispatch("visit_for", node)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self._dispatch("visit_global", node)
        self.generic_visit(node)


def analyze_source(
    source: str,
    filename: str = "<string>",
    *,
    rules: Optional[Sequence[str]] = None,
    disabled: Optional[Sequence[str]] = None,
    line_offset: int = 0,
    assume_trial_classes: Optional[Set[str]] = None,
    _program: bool = True,
) -> List[Diagnostic]:
    """Analyze one module's source; returns sorted diagnostics.

    Program-level rules (the concurrency pass) run over this one module
    too, so a self-contained fixture shows its lock cycle without a
    directory; ``analyze_path``/``analyze_paths`` pass ``_program=False``
    per file and run ONE cross-module pass over the whole target instead.
    """
    from determined_tpu.lint.rules import build_rules

    ctx = LintContext(
        filename,
        source,
        line_offset=line_offset,
        assume_trial_classes=assume_trial_classes,
    )
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [
            Diagnostic(
                rule="parse-error",
                severity=ERROR,
                message=f"cannot parse: {e.msg}",
                file=filename,
                line=(e.lineno or 1) + line_offset,
                col=e.offset or 0,
            )
        ]
    rule_objs = build_rules(only=rules, disabled=disabled)
    walker_rules = [r for r in rule_objs if not r.program_level]
    program_rules = [r for r in rule_objs if r.program_level]
    for rule in walker_rules:
        rule.before_module(tree, ctx)
    _Walker(ctx, walker_rules).visit(tree)
    diags = list(ctx.diagnostics)
    if _program and program_rules:
        from determined_tpu.lint._concurrency import analyze_program_sources

        diags.extend(
            analyze_program_sources(
                {filename: source},
                program_rules,
                line_offsets={filename: line_offset},
            )
        )
    return sorted(diags, key=lambda d: (d.file, d.line, d.col, d.rule))


def analyze_file(path: str, **kwargs: Any) -> List[Diagnostic]:
    with open(path, encoding="utf-8") as f:
        return analyze_source(f.read(), filename=path, **kwargs)


def analyze_paths(
    paths: Sequence[str],
    *,
    rules: Optional[Sequence[str]] = None,
    disabled: Optional[Sequence[str]] = None,
    exclude: Optional[Sequence[str]] = None,
    **file_kwargs: Any,
) -> List[Diagnostic]:
    """Lint several files/directories as ONE program.

    Per-module rules run file by file; the program-level concurrency pass
    runs once over the union, so a lock bound in one target and acquired
    under another target's lock still forms a graph edge (``scripts/`` and
    ``bench.py`` import ``determined_tpu`` — their lock use belongs in the
    package's graph, which is why ``scripts/lint.sh`` passes every target
    in a single invocation).

    Extra keyword args (``assume_trial_classes`` etc.) are forwarded to
    the per-module ``analyze_source`` pass for every file, keeping
    ``analyze_path``'s directory mode on its historical contract.

    ``exclude``: fnmatch globs (matched against basenames and
    target-relative paths, pruning whole directories) — dir-mode over a
    live experiment checkout must skip journal/checkpoint/trace artifacts
    and shipped context code (``dtpu lint . --exclude 'checkpoints/*'``).
    """
    from determined_tpu.lint._concurrency import (
        analyze_program_sources,
        collect_py_files,
    )
    from determined_tpu.lint.rules import build_rules

    rule_objs = build_rules(only=rules, disabled=disabled)
    program_rules = [r.id for r in rule_objs if r.program_level]
    files: List[str] = []
    seen_real: Set[str] = set()
    for path in paths:
        for f in collect_py_files(path, exclude=tuple(exclude or ())):
            # overlapping targets can spell one physical file two ways
            # (`dtpu lint pkg ./pkg/mod.py`); linting it twice doubles
            # every finding and forks its module identity in the index
            key = os.path.realpath(f)
            if key not in seen_real:
                seen_real.add(key)
                files.append(f)
    out: List[Diagnostic] = []
    sources: Dict[str, str] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
        out.extend(
            analyze_source(
                sources[f], filename=f, rules=rules, disabled=disabled,
                _program=False, **file_kwargs,
            )
        )
    if program_rules:
        program_objs = [r for r in rule_objs if r.program_level]
        out.extend(analyze_program_sources(sources, program_objs))
    return sorted(out, key=lambda d: (d.file, d.line, d.col, d.rule))


def analyze_path(path: str, **kwargs: Any) -> List[Diagnostic]:
    """Lint a .py file or recursively every .py file under a directory
    (one whole-program concurrency pass across the directory).  Accepts
    ``exclude=`` globs in directory mode (see ``analyze_paths``)."""
    if os.path.isfile(path):
        kwargs.pop("exclude", None)  # a named file is always linted
        return analyze_file(path, **kwargs)
    return analyze_paths([path], **kwargs)


def analyze_class(trial_cls: type, **kwargs: Any) -> List[Diagnostic]:
    """Lint an imported JaxTrial subclass via ``inspect.getsource``.

    Diagnostics carry real ``file:line`` anchors (the class's source file
    and absolute line numbers).  Raises ``OSError`` when source is
    unavailable (REPL-defined classes) — callers decide whether that is
    fatal (CLI) or skippable (preflight warn mode).
    """
    import inspect

    src_lines, start = inspect.getsourcelines(trial_cls)
    filename = inspect.getsourcefile(trial_cls) or f"<{trial_cls.__qualname__}>"
    source = textwrap.dedent("".join(src_lines))
    return analyze_source(
        source,
        filename=filename,
        line_offset=start - 1,
        assume_trial_classes={trial_cls.__name__},
        **kwargs,
    )


def analyze_entrypoint(spec: str, **kwargs: Any) -> List[Diagnostic]:
    """Lint a ``pkg.module:ClassName`` entrypoint (imports the module)."""
    import importlib

    module_name, _, class_name = spec.partition(":")
    module = importlib.import_module(module_name)
    if not class_name:
        path = getattr(module, "__file__", None)
        if path is None:
            raise ValueError(f"module {module_name} has no source file")
        return analyze_file(path, **kwargs)
    return analyze_class(getattr(module, class_name), **kwargs)
