"""Whole-program SPMD correctness analysis: rank-divergence hazards.

This is the third whole-program pass of the preflight analyzer (after the
per-module walker in ``_ast.py`` and the concurrency pass in
``_concurrency.py``).  The failure mode it targets is specific to
multi-host gangs and is the worst one distributed training has: not a
crash but a **hang** — one rank takes a different code path, issues a
different (or no) collective, and every healthy rank blocks into the
600-second collective timeout with zero diagnostics.  We have hit this
class live twice (the ``_drain_pending_save`` healthy-ranks-hang, the
gloo checkpoint-thread/psum interleave SIGABRT), both found by humans
staring at stack dumps.

The pass reuses the concurrency pass's ``ProgramIndex`` (module/class/
function index, cross-module call resolution, witness chains) and drives
five rules over it:

- **rank-dependent-collective** — an ``if``/``elif`` conditioned on rank
  (``jax.process_index()``, ``dist.rank``, ``is_chief``, ``DTPU_RANK``
  env) whose branches reach DIFFERENT collective sets.  One rank enters
  a collective the others never issue.
- **conditional-collective-escape** — a guarded ``raise``/``return``/
  ``break`` between two collectives (or a rank-dependent loop around
  one): the path where one rank exits the collective sequence early and
  the rest block forever.  The blessed fix — exchange the local fact
  first, then escape on the *exchanged* value so every rank escapes
  together (``Trainer._drain_pending_save``) — is recognized: a guard
  that references a value derived from a collective result is
  rank-uniform and exempt.
- **unordered-iteration-feeding-collective** — iteration over ``set``/
  ``frozenset``/``os.listdir``/``glob``/``iterdir`` (genuinely
  unordered or order-unstable across processes) that issues collectives
  per element or builds a payload a later collective carries: ranks
  agree on the elements but not the order, so their collective
  sequences interleave differently.
- **rank-guarded-io-missing-barrier** — a chief-only write followed by
  an unguarded read with no collective between them: non-chief ranks
  race the chief's filesystem effects.
- **wall-clock-divergence** — ``time.*``/unseeded ``random``/``uuid``
  controlling whether a collective runs ("save every 60s"), or riding
  an operand that must match across ranks.  Clocks and unseeded RNG are
  the sneakiest rank-divergent inputs because they differ on every host
  *every run*.  ``broadcast`` of such a value is the fix (one rank's
  sample, distributed) and is exempt.

Detection is deliberately conservative and syntactic where resolution
would guess: a collective is a ``jax.lax`` collective by name, a
``multihost_utils`` entry point, or a ``gather/allgather/broadcast/
barrier/...`` method on a receiver that is recognizably a distributed
context (``dist``, ``self._dist``, ``self.core.distributed``, the
``_global``/``_local`` stars).  An unresolvable call contributes
nothing, so every finding names a concrete path.  The runtime companion
is ``lint/_runtime.py``'s ``CollectiveSequenceSentinel``, which checks
the ACTUAL per-rank collective sequence the same way the lock-order
sentinel checks actual acquisitions.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from determined_tpu.lint._ast import dotted_name
from determined_tpu.lint._concurrency import (
    FuncInfo,
    ProgramIndex,
    _Reporter,
    _chain_str,
    _walk_pruning_defs,
)
from determined_tpu.lint._diag import Diagnostic

#: jax.lax tensor-plane collectives (by last name segment; the full name
#: must look like a lax/jax call so a stray method of the same name on an
#: unrelated object stays quiet)
_TENSOR_COLLECTIVES = frozenset(
    {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "all_to_all",
        "ppermute",
        "pshuffle",
        "psum_scatter",
    }
)
#: jax.experimental.multihost_utils entry points (unambiguous names:
#: match on the last segment wherever they appear)
_MULTIHOST_COLLECTIVES = frozenset(
    {
        "sync_global_devices",
        "process_allgather",
        "broadcast_one_to_all",
    }
)
#: control-plane collective METHODS (DistributedContext and the _Star
#: transports under it)
_DIST_METHODS = frozenset(
    {
        "allgather",
        "gather",
        "broadcast",
        "barrier",
        "allgather_local",
        "gather_local",
        "broadcast_local",
        "scatter_same",
    }
)
#: receiver name tails that identify a distributed context: the final
#: segment of the receiver's dotted name (``self.core.distributed`` ->
#: ``distributed``); class-based resolution through the index backs this
#: up when the attr's ctor is visible
_DIST_RECEIVER_TAILS = frozenset(
    {
        "dist",
        "_dist",
        "distributed",
        "_distributed",
        "distributed_context",
        "_global",
        "_local",
        "star",
        "_star",
    }
)
#: one-rank-payload ops: the canonical FIX for divergent inputs (chief
#: samples, everyone receives the same value) — exempt from the
#: wall-clock-divergence operand check
_BROADCAST_OPS = frozenset({"broadcast", "broadcast_local", "broadcast_one_to_all"})

#: attribute reads that carry the process's rank identity
_RANK_ATTRS = frozenset(
    {
        "rank",
        "group_rank",
        "local_rank",
        "cross_rank",
        "node_rank",
        "process_rank",
        "is_chief",
        "is_local_chief",
        "process_index",
    }
)
#: attributes that look rank-adjacent but are rank-UNIFORM (same value on
#: every process) — branching on these is safe and must never be flagged
_UNIFORM_ATTRS = frozenset({"size", "local_size", "cross_size", "process_count"})
#: bare names that carry rank identity (parameters, rendezvous locals)
_RANK_NAMES = frozenset(
    {
        "rank",
        "group_rank",
        "local_rank",
        "cross_rank",
        "node_rank",
        "process_rank",
        "is_chief",
        "is_local_chief",
    }
)
#: call name tails returning the process's rank
_RANK_CALL_TAILS = frozenset({"process_index"})

#: wall-clock / unseeded-randomness sources (full dotted name prefixes)
_DIVERGENT_PREFIXES = (
    "time.",
    "datetime.",
    "random.",
    "np.random.",
    "numpy.random.",
    "secrets.",
)
_DIVERGENT_CALLS = frozenset(
    {
        "time.time",
        "time.monotonic",
        "time.perf_counter",
        "time.time_ns",
        "time.monotonic_ns",
        "datetime.now",
        "datetime.utcnow",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
        "os.urandom",
        "uuid.uuid1",
        "uuid.uuid4",
        "uuid_mod.uuid1",
        "uuid_mod.uuid4",
    }
)

#: unordered (or cross-process order-unstable) iteration sources, by
#: callable last segment
_UNORDERED_ITER_TAILS = frozenset(
    {"listdir", "iterdir", "scandir", "glob", "iglob"}
)

#: write-effect call tails for the rank-guarded-io rule
_WRITE_IO_TAILS = frozenset(
    {
        "makedirs",
        "mkdir",
        "write_text",
        "write_bytes",
        "dump",
        "save",
        "save_arrays",
        "save_trainer_state",
        "rename",
        "replace",
        "copyfile",
        "copytree",
        "copy2",
        "move",
        "symlink",
    }
)
#: read-effect call tails (what non-chief ranks race on)
_READ_IO_TAILS = frozenset(
    {
        "load",
        "read_text",
        "read_bytes",
        "load_arrays",
        "getsize",
        "getmtime",
    }
)

_MAX_CALL_DEPTH = 8


# ---------------------------------------------------------------------------
# collective detection
# ---------------------------------------------------------------------------


def collective_label(
    index: ProgramIndex, fn: FuncInfo, node: ast.Call
) -> Optional[str]:
    """Op label ("psum", "allgather", ...) when this call is a collective,
    else None."""
    name = dotted_name(node.func)
    if not name:
        return None
    parts = name.split(".")
    tail = parts[-1]
    if tail in _MULTIHOST_COLLECTIVES:
        return tail
    if tail in _TENSOR_COLLECTIVES:
        # jax.lax.psum / lax.psum / jax.psum — require a jax-ish prefix so
        # an unrelated object's method of the same name stays quiet
        # (DistributedContext has no such methods; `all_gather` etc. only
        # exist on jax modules in this codebase)
        if len(parts) == 1 or parts[0] in ("jax", "lax", "jnp", "pl", "plgpu"):
            return tail
        return None
    if tail in _DIST_METHODS and isinstance(node.func, ast.Attribute):
        recv = node.func.value
        recv_name = dotted_name(recv)
        if recv_name:
            recv_tail = recv_name.split(".")[-1]
            if recv_tail in _DIST_RECEIVER_TAILS:
                return tail
            # class-based resolution: `self.comm.allgather(...)` where
            # __init__ shows `self.comm = DistributedContext(...)`
            recv_parts = recv_name.split(".")
            if (
                recv_parts[0] == "self"
                and len(recv_parts) == 2
                and fn.cls is not None
            ):
                ctor = fn.cls.attr_ctors.get(recv_parts[1], "")
                if "Distributed" in ctor.split(".")[-1]:
                    return tail
    return None


def _is_rank_env_read(node: ast.Call) -> bool:
    """``os.environ.get("DTPU_RANK")`` / ``os.getenv("...RANK...")``."""
    name = dotted_name(node.func) or ""
    if name not in ("os.environ.get", "os.getenv", "environ.get", "getenv"):
        return False
    for arg in node.args[:1]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return "RANK" in arg.value.upper()
    return False


def _is_divergent_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if not name:
        return False
    if name in _DIVERGENT_CALLS:
        return True
    if name.startswith(("np.random.", "numpy.random.", "random.", "secrets.")):
        # unseeded module-level randomness; an rng OBJECT built from an
        # explicit seed (`rng = random.Random(seed)`) has a different
        # receiver and is never matched here
        return name.split(".")[-1] not in ("Random", "default_rng", "seed")
    return False


class _FnFacts:
    """Per-function taint facts: which local names carry rank identity,
    which are rank-uniform (derived from a collective's result), which
    carry wall-clock/unseeded-random values."""

    __slots__ = ("rank", "uniform", "divergent")

    def __init__(self) -> None:
        self.rank: Set[str] = set()
        self.uniform: Set[str] = set()
        self.divergent: Set[str] = set()


def _assigned_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
    return out


def _expr_calls(expr: ast.AST):
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            yield sub


def _expr_has_rank_source(
    index: ProgramIndex, fn: FuncInfo, expr: ast.AST, facts: Optional[_FnFacts]
) -> bool:
    """Does this expression read the process's rank identity?"""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr in _RANK_ATTRS:
            # `.process_index` as a method REFERENCE is caught by the call
            # check; the attribute read form (`dist.rank`) lands here
            return True
        if isinstance(sub, ast.Name):
            if sub.id in _RANK_NAMES:
                return True
            if facts is not None and sub.id in facts.rank:
                return True
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func) or ""
            if name.split(".")[-1] in _RANK_CALL_TAILS:
                return True
            if _is_rank_env_read(sub):
                return True
    return False


def _expr_has_uniform_source(
    index: ProgramIndex, fn: FuncInfo, expr: ast.AST, facts: _FnFacts
) -> bool:
    """Does this expression reference a value every rank computed
    identically (a collective's result, or a name derived from one)?"""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and sub.id in facts.uniform:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _UNIFORM_ATTRS:
            return True
        if isinstance(sub, ast.Call) and collective_label(index, fn, sub):
            return True
    return False


def _expr_has_divergent_source(expr: ast.AST, facts: _FnFacts) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and _is_divergent_call(sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in facts.divergent:
            return True
    return False


def _compute_facts(index: ProgramIndex, fn: FuncInfo) -> _FnFacts:
    """Two forward passes of name-level taint over the function body
    (flow-insensitive, like the step-taint in ``_ast.py``): collective
    results make names rank-UNIFORM; rank/clock sources make them
    rank-dependent/divergent.  Uniform wins on reassignment from a
    collective — that ordering is what blesses the exchange-then-escape
    idiom."""
    facts = _FnFacts()
    body = getattr(fn.node, "body", [])
    for _ in range(2):
        for stmt in body:
            for sub in _walk_pruning_defs(stmt):
                pairs: List[Tuple[ast.AST, ast.AST]] = []
                if isinstance(sub, ast.Assign) and sub.value is not None:
                    pairs = [(t, sub.value) for t in sub.targets]
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    pairs = [(sub.target, sub.value)]
                elif isinstance(sub, ast.AugAssign):
                    pairs = [(sub.target, sub.value)]
                for target, value in pairs:
                    names = _assigned_names(target)
                    if not names:
                        continue
                    has_collective = any(
                        collective_label(index, fn, c) for c in _expr_calls(value)
                    )
                    if has_collective:
                        # the exchanged value is identical on every rank
                        facts.uniform |= names
                        facts.rank -= names
                        facts.divergent -= names
                        continue
                    if _expr_has_rank_source(index, fn, value, facts):
                        facts.rank |= names
                    if _expr_has_divergent_source(value, facts):
                        facts.divergent |= names
                    if _expr_has_uniform_source(index, fn, value, facts):
                        facts.uniform |= names
    return facts


# ---------------------------------------------------------------------------
# transitive collective summaries
# ---------------------------------------------------------------------------


class SpmdAnalyzer:
    """Memoized per-function facts + transitive collective summaries."""

    def __init__(self, index: ProgramIndex) -> None:
        self.index = index
        self._facts: Dict[int, _FnFacts] = {}
        self._summaries: Dict[int, Dict[str, Tuple[str, ...]]] = {}
        self._in_progress: Set[int] = set()

    def facts(self, fn: FuncInfo) -> _FnFacts:
        key = id(fn)
        if key not in self._facts:
            self._facts[key] = _compute_facts(self.index, fn)
        return self._facts[key]

    def summary(self, fn: FuncInfo, depth: int = 0) -> Dict[str, Tuple[str, ...]]:
        """op label -> witness chain of ``qname:line`` hops, transitively
        through resolvable calls.  Truncated (depth/recursion) summaries
        are never cached — same contract as the concurrency analyzer."""
        return self._summary_impl(fn, depth)[0]

    def _summary_impl(
        self, fn: FuncInfo, depth: int
    ) -> Tuple[Dict[str, Tuple[str, ...]], bool]:
        key = id(fn)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached, True
        out: Dict[str, Tuple[str, ...]] = {}
        if depth > _MAX_CALL_DEPTH or key in self._in_progress:
            return out, False
        complete = True
        self._in_progress.add(key)
        try:
            for sub in _walk_pruning_defs(fn.node):
                if not isinstance(sub, ast.Call):
                    continue
                site = f"{fn.qname}:{getattr(sub, 'lineno', 0)}"
                label = collective_label(self.index, fn, sub)
                if label is not None:
                    out.setdefault(label, (site,))
                    continue
                callee = self.index.resolve_call(fn, sub)
                if callee is not None and callee is not fn:
                    inner, sub_complete = self._summary_impl(callee, depth + 1)
                    complete = complete and sub_complete
                    for op, chain in inner.items():
                        out.setdefault(op, (site,) + chain)
        finally:
            self._in_progress.discard(key)
        if complete:
            self._summaries[key] = out
        return out, complete

    def stmts_collectives(
        self, fn: FuncInfo, stmts: Sequence[ast.stmt]
    ) -> Dict[str, Tuple[str, ...]]:
        """Collective ops reachable from a statement list (direct calls
        plus transitive through resolvable calls), with witness chains."""
        out: Dict[str, Tuple[str, ...]] = {}
        for stmt in stmts:
            for sub in _walk_pruning_defs(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                site = f"{fn.qname}:{getattr(sub, 'lineno', 0)}"
                label = collective_label(self.index, fn, sub)
                if label is not None:
                    out.setdefault(label, (site,))
                    continue
                callee = self.index.resolve_call(fn, sub)
                if callee is not None and callee is not fn:
                    for op, chain in self.summary(callee, 1).items():
                        out.setdefault(op, (site,) + chain)
        return out

    def all_functions(self) -> List[FuncInfo]:
        out: List[FuncInfo] = []

        def add(fn: FuncInfo) -> None:
            out.append(fn)
            for child in fn.children.values():
                add(child)

        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                add(fn)
            for cls in mod.classes.values():
                for fn in cls.methods.values():
                    add(fn)
        return out


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _fmt_ops(ops: Dict[str, Tuple[str, ...]]) -> str:
    return ", ".join(
        f"`{op}` (via {_chain_str(chain)})" for op, chain in sorted(ops.items())
    )


def _check_rank_dependent_collective(
    analyzer: SpmdAnalyzer, reporter: _Reporter, rule: Any, fn: FuncInfo
) -> None:
    facts = analyzer.facts(fn)
    for sub in _walk_pruning_defs(fn.node):
        if not isinstance(sub, ast.If):
            continue
        if not _expr_has_rank_source(analyzer.index, fn, sub.test, facts):
            continue
        if _expr_has_uniform_source(analyzer.index, fn, sub.test, facts):
            # the branch decision came out of a collective: every rank
            # takes the same side
            continue
        body_ops = analyzer.stmts_collectives(fn, sub.body)
        else_ops = analyzer.stmts_collectives(fn, sub.orelse)
        if not body_ops and not else_ops:
            continue
        # compare the SETS of ops: branches that reach the same collective
        # set through different paths (error vs ok broadcast in
        # restore_path) stay legal; a set difference means some rank
        # skips (or adds) a collective entirely
        missing = set(body_ops) ^ set(else_ops)
        if not missing:
            continue
        one_sided = {
            op: (body_ops.get(op) or else_ops.get(op) or ())
            for op in sorted(missing)
        }
        reporter.report(
            rule,
            fn.module,
            sub,
            "collective guarded by a rank-dependent condition: "
            f"{_fmt_ops(one_sided)} runs on only one side of this branch, "
            "so ranks on the other side never enter it and the gang hangs "
            "to the collective timeout; either run the collective on every "
            "rank (exchange the fact, then branch on the result) or hoist "
            "it out of the rank test",
        )


class _Escape:
    __slots__ = ("node", "kind", "guard", "loop")

    def __init__(self, node: ast.stmt, kind: str, guard: Optional[ast.AST],
                 loop: Optional[ast.AST]) -> None:
        self.node = node
        self.kind = kind
        self.guard = guard
        self.loop = loop


def _collect_escapes(fn: FuncInfo) -> List[_Escape]:
    """Guarded ``raise``/``return``/``break`` statements.  ``guard`` is
    the innermost enclosing If's test (None = unconditional: every rank
    takes it together, not a divergence).  Escapes inside ``except``
    handlers are excluded: exception paths out of a failed collective are
    the transport's own error propagation, not a code-path split."""
    out: List[_Escape] = []

    def walk(node: ast.AST, guard: Optional[ast.AST], loop: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.If):
            for child in node.body:
                walk(child, node.test, loop)
            for child in node.orelse:
                # elif chains nest as If-in-orelse and re-guard themselves
                walk(child, node.test, loop)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            inner_loop = node
            for child in node.body:
                walk(child, guard, inner_loop)
            for child in node.orelse:
                walk(child, guard, loop)
            return
        if isinstance(node, ast.Try):
            for child in node.body:
                walk(child, guard, loop)
            for child in node.orelse:
                walk(child, guard, loop)
            for child in node.finalbody:
                walk(child, guard, loop)
            return  # handlers skipped by design
        if isinstance(node, ast.Raise) and guard is not None:
            out.append(_Escape(node, "raise", guard, loop))
        elif isinstance(node, ast.Return) and guard is not None:
            out.append(_Escape(node, "return", guard, loop))
        elif isinstance(node, ast.Break) and guard is not None:
            out.append(_Escape(node, "break", guard, loop))
        for child in ast.iter_child_nodes(node):
            walk(child, guard, loop)

    for stmt in getattr(fn.node, "body", []):
        walk(stmt, None, None)
    return out


def _collective_sites(
    analyzer: SpmdAnalyzer, fn: FuncInfo
) -> List[Tuple[int, str, Tuple[str, ...]]]:
    """(line, op, chain) for every point in this function that reaches a
    collective — direct calls and resolvable calls whose summaries
    contain one."""
    out: List[Tuple[int, str, Tuple[str, ...]]] = []
    for sub in _walk_pruning_defs(fn.node):
        if not isinstance(sub, ast.Call):
            continue
        line = getattr(sub, "lineno", 0)
        site = f"{fn.qname}:{line}"
        label = collective_label(analyzer.index, fn, sub)
        if label is not None:
            out.append((line, label, (site,)))
            continue
        callee = analyzer.index.resolve_call(fn, sub)
        if callee is not None and callee is not fn:
            for op, chain in analyzer.summary(callee, 1).items():
                out.append((line, op, (site,) + chain))
    out.sort()
    return out


def _check_conditional_collective_escape(
    analyzer: SpmdAnalyzer, reporter: _Reporter, rule: Any, fn: FuncInfo
) -> None:
    facts = analyzer.facts(fn)
    # escape analysis covers HOST-side collectives only (control-plane
    # stars, multihost_utils).  Tensor-plane ops (psum/ppermute/...) live
    # in traced code where jax itself forbids branching on runtime values:
    # a Python guard there is resolved ONCE at trace time from config, so
    # an "escape" is the same trace-time decision on every rank, not a
    # runtime divergence.  (A rank-DEPENDENT guard in traced code still
    # traces different programs per rank — the rank-dependent-collective
    # and loop checks below cover that, tensor ops included.)
    sites = [
        s for s in _collective_sites(analyzer, fn)
        if s[1] not in _TENSOR_COLLECTIVES
    ]

    # -- rank-dependent loops around collectives ---------------------------
    for sub in _walk_pruning_defs(fn.node):
        trip_expr: Optional[ast.AST] = None
        if isinstance(sub, (ast.For, ast.AsyncFor)):
            trip_expr = sub.iter
        elif isinstance(sub, ast.While):
            trip_expr = sub.test
        if trip_expr is None:
            continue
        if not _expr_has_rank_source(analyzer.index, fn, trip_expr, facts):
            continue
        if _expr_has_uniform_source(analyzer.index, fn, trip_expr, facts):
            continue
        ops = analyzer.stmts_collectives(fn, sub.body)
        if ops:
            reporter.report(
                rule,
                fn.module,
                sub,
                f"collective inside a loop whose trip count is "
                f"rank-dependent: {_fmt_ops(ops)} — ranks run DIFFERENT "
                "numbers of iterations, so one rank's extra collective has "
                "no partner and the gang hangs; derive the trip count from "
                "rank-uniform data (exchange it first) or hoist the "
                "collective out of the loop",
            )

    if not sites:
        return

    # -- guarded escapes between collectives -------------------------------
    for esc in _collect_escapes(fn):
        guard = esc.guard
        assert guard is not None
        if _expr_has_uniform_source(analyzer.index, fn, guard, facts):
            # exchange-then-escape: the guard came out of a collective, so
            # every rank escapes together (the _drain_pending_save idiom)
            continue
        line = getattr(esc.node, "lineno", 0)
        if esc.kind == "break":
            loop = esc.loop
            if loop is None:
                continue
            ops = {
                op: chain
                for op, chain in analyzer.stmts_collectives(fn, loop.body).items()
                if op not in _TENSOR_COLLECTIVES
            }
            if ops:
                reporter.report(
                    rule,
                    fn.module,
                    esc.node,
                    f"conditional `break` inside a collective loop "
                    f"({_fmt_ops(ops)}): a rank whose local condition fires "
                    "stops issuing collectives while its peers keep going; "
                    "exchange the stop decision (allgather the flag, break "
                    "on any()) so every rank leaves the loop on the same "
                    "iteration",
                )
            continue
        before = [s for s in sites if s[0] < line]
        after = [s for s in sites if s[0] > line]
        if not before or not after:
            continue
        b_line, b_op, b_chain = before[-1]
        a_line, a_op, a_chain = after[0]
        reporter.report(
            rule,
            fn.module,
            esc.node,
            f"conditional `{esc.kind}` between collectives: a rank whose "
            f"local condition fires leaves after `{b_op}` (line {b_line}) "
            f"and never reaches `{a_op}` (via {_chain_str(a_chain)}), so "
            "the remaining ranks block there until the collective timeout; "
            "exchange the local fact first (allgather it) and escape on "
            "the exchanged value so every rank escapes together",
        )


def _unordered_iter_reason(node: ast.AST) -> Optional[str]:
    """Why this iteration source has no cross-process order, or None."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set"
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if not name:
            return None
        tail = name.split(".")[-1]
        if tail in ("set", "frozenset") and len(name.split(".")) == 1:
            return f"`{tail}(...)`"
        if tail in _UNORDERED_ITER_TAILS:
            return f"`{name}(...)` (filesystem enumeration order)"
    if isinstance(node, ast.Attribute) and dotted_name(node) == "os.environ":
        return "`os.environ` (environment order differs across hosts)"
    return None


def _check_unordered_iteration(
    analyzer: SpmdAnalyzer, reporter: _Reporter, rule: Any, fn: FuncInfo
) -> None:
    # names appended/extended inside unordered loops, to catch payloads a
    # LATER collective carries
    deferred: List[Tuple[ast.AST, str, Set[str]]] = []
    for sub in _walk_pruning_defs(fn.node):
        if not isinstance(sub, (ast.For, ast.AsyncFor)):
            continue
        reason = _unordered_iter_reason(sub.iter)
        if reason is None:
            continue
        ops = analyzer.stmts_collectives(fn, sub.body)
        if ops:
            reporter.report(
                rule,
                fn.module,
                sub,
                f"collective issued while iterating {reason}: "
                f"{_fmt_ops(ops)} — iteration order is not guaranteed to "
                "match across ranks, so their collective sequences "
                "interleave differently and the gang deadlocks or merges "
                "the wrong pairs; iterate `sorted(...)` instead",
            )
            continue
        grown: Set[str] = set()
        for inner in sub.body:
            for call in _walk_pruning_defs(inner):
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("append", "extend", "add", "update")
                    and isinstance(call.func.value, ast.Name)
                ):
                    grown.add(call.func.value.id)
        if grown:
            deferred.append((sub, reason, grown))
    if not deferred:
        return
    for sub in _walk_pruning_defs(fn.node):
        if not isinstance(sub, ast.Call):
            continue
        label = collective_label(analyzer.index, fn, sub)
        if label is None:
            continue
        arg_names = {
            n.id
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]
            for n in ast.walk(arg)
            if isinstance(n, ast.Name)
        }
        for loop_node, reason, grown in deferred:
            hit = arg_names & grown
            if hit and getattr(sub, "lineno", 0) > getattr(loop_node, "lineno", 0):
                reporter.report(
                    rule,
                    fn.module,
                    loop_node,
                    f"payload `{sorted(hit)[0]}` is built while iterating "
                    f"{reason} and later crosses `{label}` (line "
                    f"{getattr(sub, 'lineno', 0)}): element order differs "
                    "across ranks, so the exchanged payloads disagree even "
                    "when their contents match; build it from `sorted(...)`",
                )
                break


def _open_write_mode(node: ast.Call) -> Optional[bool]:
    """True write-mode open, False read-mode open, None not an open."""
    name = dotted_name(node.func)
    if not name or name.split(".")[-1] != "open":
        return None
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str):
        return any(c in mode for c in "wax+")
    return False  # bare open(path): read


def _io_kind(node: ast.Call) -> Optional[str]:
    """"write" / "read" classification for the rank-guarded-io rule."""
    is_write = _open_write_mode(node)
    if is_write is not None:
        return "write" if is_write else "read"
    name = dotted_name(node.func)
    if not name:
        return None
    tail = name.split(".")[-1]
    if tail in _WRITE_IO_TAILS:
        return "write"
    if tail in _READ_IO_TAILS:
        return "read"
    if tail in ("exists", "isfile", "isdir", "stat"):
        # probing for the chief's output is the canonical racy read
        return "read"
    return None


def _check_rank_guarded_io(
    analyzer: SpmdAnalyzer, reporter: _Reporter, rule: Any, fn: FuncInfo
) -> None:
    facts = analyzer.facts(fn)
    # ordered event stream: (line, kind, node) where kind is
    # "guard_write" (rank-guarded If containing a write), "sync"
    # (collective), or "read" (unguarded read)
    events: List[Tuple[int, str, ast.AST, str]] = []

    def walk(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.If) and _expr_has_rank_source(
            analyzer.index, fn, node.test, facts
        ):
            writes = [
                sub
                for child in node.body
                for sub in _walk_pruning_defs(child)
                if isinstance(sub, ast.Call) and _io_kind(sub) == "write"
            ]
            if writes:
                end = getattr(node, "end_lineno", getattr(node, "lineno", 0))
                events.append((end, "guard_write", node, ""))
            for child in node.body:
                walk(child, True)
            for child in node.orelse:
                walk(child, True)
            return
        if isinstance(node, ast.Call):
            label = collective_label(analyzer.index, fn, node)
            if label is not None:
                events.append((getattr(node, "lineno", 0), "sync", node, label))
            else:
                callee = analyzer.index.resolve_call(fn, node)
                if callee is not None and callee is not fn and analyzer.summary(
                    callee, 1
                ):
                    events.append((getattr(node, "lineno", 0), "sync", node, "call"))
                elif not guarded and _io_kind(node) == "read":
                    events.append((getattr(node, "lineno", 0), "read", node, ""))
        for child in ast.iter_child_nodes(node):
            walk(child, guarded)

    for stmt in getattr(fn.node, "body", []):
        walk(stmt, False)

    events.sort(key=lambda e: e[0])
    pending_guard: Optional[Tuple[int, ast.AST]] = None
    for line, kind, node, _label in events:
        if kind == "guard_write":
            pending_guard = (line, node)
        elif kind == "sync":
            pending_guard = None
        elif kind == "read" and pending_guard is not None:
            g_line = getattr(pending_guard[1], "lineno", 0)
            reporter.report(
                rule,
                fn.module,
                node,
                f"read of filesystem state the rank-guarded write (line "
                f"{g_line}) produces, with no collective between them: "
                "non-chief ranks race the chief's write and read a "
                "missing or half-written file; put a `barrier()` (or any "
                "collective) between the chief-only write and the "
                "all-rank read",
            )
            pending_guard = None  # one finding per guard/read pair


def _check_wall_clock_divergence(
    analyzer: SpmdAnalyzer, reporter: _Reporter, rule: Any, fn: FuncInfo
) -> None:
    facts = analyzer.facts(fn)
    # (a) clock/rng-guarded collectives: "save every 60 seconds" — each
    # rank's clock fires on a different step, so their sequences diverge
    for sub in _walk_pruning_defs(fn.node):
        test: Optional[ast.AST] = None
        if isinstance(sub, ast.If):
            test = sub.test
        elif isinstance(sub, ast.While):
            test = sub.test
        if test is None:
            continue
        if not _expr_has_divergent_source(test, facts):
            continue
        if _expr_has_uniform_source(analyzer.index, fn, test, facts):
            continue
        ops = analyzer.stmts_collectives(fn, sub.body)
        if ops:
            reporter.report(
                rule,
                fn.module,
                sub,
                f"collective guarded by wall-clock/unseeded randomness: "
                f"{_fmt_ops(ops)} — each rank's clock or RNG fires at a "
                "different moment, so ranks disagree on WHETHER to enter "
                "the collective and the gang hangs; decide from a "
                "rank-uniform quantity (step count) or let the chief "
                "decide and `broadcast` the decision",
            )
    # (b) divergent operand crossing an exchange whose payloads must be
    # comparable (allgather/tensor collectives); broadcast and gather are
    # exempt — one-rank payload and chief-consumed diagnostics
    for sub in _walk_pruning_defs(fn.node):
        if not isinstance(sub, ast.Call):
            continue
        label = collective_label(analyzer.index, fn, sub)
        if label is None or label in _BROADCAST_OPS or label.startswith("gather"):
            continue
        if label in ("barrier",):
            continue
        for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
            if _expr_has_divergent_source(arg, facts):
                reporter.report(
                    rule,
                    fn.module,
                    sub,
                    f"wall-clock/unseeded-random value crosses `{label}`: "
                    "every rank contributes a different sample, so "
                    "downstream decisions made from the merged result "
                    "diverge run to run and rank to rank; journal a seed, "
                    "derive the value from rank-uniform state, or have "
                    "the chief sample once and `broadcast` it",
                )
                break


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def run_spmd_pass(index: ProgramIndex, rules: Sequence[Any]) -> List[Diagnostic]:
    by_id = {r.id: r for r in rules}
    rank_rule = by_id.get("rank-dependent-collective")
    escape_rule = by_id.get("conditional-collective-escape")
    unordered_rule = by_id.get("unordered-iteration-feeding-collective")
    io_rule = by_id.get("rank-guarded-io-missing-barrier")
    clock_rule = by_id.get("wall-clock-divergence")
    if not any((rank_rule, escape_rule, unordered_rule, io_rule, clock_rule)):
        return []
    analyzer = SpmdAnalyzer(index)
    reporter = _Reporter(index)
    for fn in analyzer.all_functions():
        if rank_rule is not None:
            _check_rank_dependent_collective(analyzer, reporter, rank_rule, fn)
        if escape_rule is not None:
            _check_conditional_collective_escape(analyzer, reporter, escape_rule, fn)
        if unordered_rule is not None:
            _check_unordered_iteration(analyzer, reporter, unordered_rule, fn)
        if io_rule is not None:
            _check_rank_guarded_io(analyzer, reporter, io_rule, fn)
        if clock_rule is not None:
            _check_wall_clock_divergence(analyzer, reporter, clock_rule, fn)
    return reporter.diagnostics
