"""Runtime sentinels: retrace detection, thread-leak checking, lock-order
tracking.

The static pass (``_ast.py``/``_concurrency.py``) catches what it can
read; these catch what only shows up live:

- **RetraceSentinel** — wraps the pre-jit step functions the Trainer
  installs (``train/_trainer.py`` / ``train/_jit_cache.py``).  jax calls
  the wrapped Python function once per TRACE, so the call count IS the
  compile count for that jitted callable: more than ``allowed`` traces of
  one logical step means the step is retrace-prone (shape-unstable
  batches, python branching on traced values, weak cache keying) and every
  extra trace is a silent full XLA compile eaten by the benchmark.  With
  the jit-reuse cache on, a healthy search stays at one trace per step
  signature — which is exactly what the sentinel asserts.
- **ThreadLeakChecker** — a context manager that snapshots live threads on
  entry and reports threads (matching ``watch`` patterns, default the
  harness's own ``dtpu-*`` workers) still alive on exit.  Tests use it to
  assert scheduler/prefetch workers die with their owners; the supervisor
  (``exec/run_trial.py``) runs trials under it in warn mode when
  ``lint.thread_sentinel`` is set.
- **LockOrderSentinel** — a test-time monkeypatch of ``threading.Lock`` /
  ``threading.RLock`` (and therefore every ``Condition``/``Event`` built
  on them afterwards) that records the process's ACTUAL lock-acquisition
  DAG and reports an inversion the moment an edge closes a cycle — the
  dynamic complement of the static ``lock-order-cycle`` rule, catching the
  dispatch the AST cannot resolve.  ``tests/conftest.py`` exposes it as
  the opt-in ``lock_order`` marker (scheduler, journal/recovery, GC, and
  observability suites run under it).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import gc
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("determined_tpu.lint.runtime")


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TraceRecord:
    """Compile accounting for one wrapped step callable."""

    label: str
    allowed: int
    traces: int = 0
    violations: int = 0


class RetraceSentinel:
    """Registry of wrapped step functions and their trace counts.

    ``wrap`` must be applied to the function BEFORE ``jax.jit``: jit then
    invokes the wrapper exactly once per trace/compile of that callable.
    Thread-safe (concurrent trials trace in parallel).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[int, TraceRecord] = {}
        self._seq = 0
        self._enabled = False

    # -- enablement (config-driven; tests flip it directly) ----------------

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- wrapping ----------------------------------------------------------

    def wrap(
        self, label: str, fn: Callable[..., Any], *, allowed: int = 1
    ) -> Callable[..., Any]:
        """Count executions of ``fn`` (= traces once jitted) under ``label``.

        ``allowed``: traces that are expected for this callable.  One for a
        train step; an eval step legitimately traces twice (the metric
        accumulator starts empty on the first validation batch, populated
        after).
        """
        with self._lock:
            self._seq += 1
            rec = TraceRecord(label=label, allowed=allowed)
            self._records[self._seq] = rec

        @functools.wraps(fn)
        def traced(*args: Any, **kwargs: Any) -> Any:
            with self._lock:
                rec.traces += 1
                over = rec.traces > rec.allowed
                if over:
                    rec.violations += 1
            if over:
                logger.warning(
                    "retrace sentinel: %s traced %d times (allowed %d) — the "
                    "step is recompiling; look for shape-unstable batches, "
                    "python branching on traced values, or hparams that "
                    "should key the jit cache (docs/lint.md)",
                    rec.label,
                    rec.traces,
                    rec.allowed,
                )
            return fn(*args, **kwargs)

        return traced

    # -- queries -----------------------------------------------------------

    def records(self) -> List[TraceRecord]:
        with self._lock:
            return [dataclasses.replace(r) for r in self._records.values()]

    def violations(self) -> Dict[str, int]:
        """label -> excess trace count, only for offenders."""
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._records.values():
                if r.violations:
                    out[r.label] = out.get(r.label, 0) + r.violations
            return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0


_retrace_sentinel = RetraceSentinel()


def get_retrace_sentinel() -> RetraceSentinel:
    """The process-global sentinel (one process = one jit cache = one
    compile ledger)."""
    return _retrace_sentinel


# ---------------------------------------------------------------------------
# thread-leak checker
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# lock-order sentinel
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LockOrderViolation:
    """An observed acquisition-order inversion: taking ``acquired`` while
    holding ``held`` closes a cycle against the edges in ``cycle``."""

    thread: str
    held: str
    acquired: str
    cycle: List[str]

    def format(self) -> str:
        return (
            f"lock-order inversion on thread {self.thread}: acquired "
            f"{self.acquired} while holding {self.held}, but the process "
            f"already acquired them in the opposite order "
            f"(cycle: {' -> '.join(self.cycle)})"
        )


class _TrackedLock:
    """Wrapper a patched ``threading.Lock``/``RLock`` factory returns.

    Delegates everything to the real primitive; ``acquire``/``release``
    additionally maintain the sentinel's per-thread held stack and the
    global acquisition DAG.  ``__getattr__`` forwards the private
    ``_release_save``/``_acquire_restore``/``_is_owned`` trio, so
    ``Condition`` built on a tracked RLock works unchanged (its ``wait``
    then bypasses the bookkeeping — conservative: the lock stays "held"
    on our stack through the wait, which can only ADD ordering edges the
    thread really did establish before waiting).
    """

    def __init__(self, sentinel: "LockOrderSentinel", inner: Any, sid: int,
                 label: str, reentrant: bool) -> None:
        self._dtpu_sentinel = sentinel
        self._dtpu_inner = inner
        self._dtpu_sid = sid
        self._dtpu_label = label
        self._dtpu_reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._dtpu_inner.acquire(blocking, timeout)
        if got:
            self._dtpu_sentinel._note_acquire(self)
        return got

    def release(self) -> None:
        self._dtpu_sentinel._note_release(self)
        self._dtpu_inner.release()

    def locked(self) -> bool:
        return self._dtpu_inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._dtpu_inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<tracked {self._dtpu_label} wrapping {self._dtpu_inner!r}>"


class LockOrderSentinel:
    """Record the live acquisition DAG; flag inversions deterministically.

    ``install()`` patches ``threading.Lock`` and ``threading.RLock`` so
    every lock created AFTERWARDS is tracked (existing locks are not —
    tests construct their subjects inside the sentinel's scope, which the
    conftest ``lock_order`` marker guarantees).  On each acquire with
    other tracked locks held, the edge ``innermost-held -> acquired`` is
    added; an edge that completes a cycle records a
    ``LockOrderViolation`` carrying both directions' witnesses.  The
    check fires on the ORDER, not on an actual deadlock, so the inversion
    is caught even when the interleaving happened to get away with it —
    that is the point: the failure is deterministic where the deadlock is
    a race.

    Locks are labeled by allocation site (``file:line#serial``), which is
    what the violation message shows.  Reentrant re-acquisition of an
    RLock adds no edges.  Not re-entrant itself: one install per process
    at a time (the conftest fixture serializes naturally).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()  # guards graph + labels + handoffs
        self._edges: Dict[int, set] = {}
        self._labels: Dict[int, str] = {}
        self._violations: List[LockOrderViolation] = []
        self._seq = 0
        self._held = threading.local()
        #: sid -> count of releases by threads that never acquired it
        #: (the legal Lock handoff pattern); the acquiring thread purges
        #: its stale stack entry lazily on its next acquire
        self._foreign_releases: Dict[int, int] = {}
        self._orig_lock: Optional[Any] = None
        self._orig_rlock: Optional[Any] = None
        self._installed = False

    # -- patching ----------------------------------------------------------

    def _alloc_site(self) -> str:
        import sys

        f = sys._getframe(2)
        while f is not None and "threading" in (f.f_code.co_filename or ""):
            f = f.f_back
        if f is None:  # pragma: no cover - interpreter internals
            return "<unknown>"
        return f"{f.f_code.co_filename}:{f.f_lineno}"

    def _make_factory(self, orig: Any, reentrant: bool) -> Any:
        def factory(*args: Any, **kwargs: Any) -> _TrackedLock:
            inner = orig(*args, **kwargs)
            with self._lock:
                self._seq += 1
                sid = self._seq
            label = f"{self._alloc_site()}#{sid}"
            with self._lock:
                self._labels[sid] = label
            return _TrackedLock(self, inner, sid, label, reentrant)

        return factory

    def install(self) -> "LockOrderSentinel":
        if self._installed:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        threading.Lock = self._make_factory(self._orig_lock, False)  # type: ignore[misc]
        threading.RLock = self._make_factory(self._orig_rlock, True)  # type: ignore[misc]
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._orig_lock  # type: ignore[misc]
        threading.RLock = self._orig_rlock  # type: ignore[misc]
        self._installed = False

    def __enter__(self) -> "LockOrderSentinel":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    # -- bookkeeping -------------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def _purge_foreign_releases(self, stack: List[int]) -> None:
        """Drop stack entries for locks some OTHER thread has since
        released (acquire-here / release-there is legal for Lock); without
        this the handed-off lock looks held forever and every later
        acquire on this thread grows a phantom ordering edge."""
        if not self._foreign_releases:  # benign unlocked read
            return
        with self._lock:
            for i in range(len(stack) - 1, -1, -1):
                n = self._foreign_releases.get(stack[i], 0)
                if n:
                    sid = stack[i]
                    del stack[i]
                    if n == 1:
                        del self._foreign_releases[sid]
                    else:
                        self._foreign_releases[sid] = n - 1

    def _note_acquire(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        self._purge_foreign_releases(stack)
        sid = lock._dtpu_sid
        if sid in stack:
            # reentrant hold (RLock, or Condition re-entry): no new order
            # information; push so the matching release pops symmetrically
            stack.append(sid)
            return
        if stack:
            holder = stack[-1]
            with self._lock:
                added = sid not in self._edges.setdefault(holder, set())
                if added:
                    self._edges[holder].add(sid)
                    cycle = self._find_cycle(sid, holder)
                    if cycle is not None:
                        self._violations.append(
                            LockOrderViolation(
                                thread=threading.current_thread().name,
                                held=self._labels.get(holder, str(holder)),
                                acquired=self._labels.get(sid, str(sid)),
                                cycle=[
                                    self._labels.get(s, str(s))
                                    for s in [holder, sid] + cycle[1:]
                                ],
                            )
                        )
        stack.append(sid)

    def _note_release(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        sid = lock._dtpu_sid
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == sid:
                del stack[i]
                return
        # released by a thread that never acquired it: cross-thread
        # handoff — the acquirer's stack entry is purged on its next
        # acquire rather than mutated from here (stacks are thread-local)
        with self._lock:
            self._foreign_releases[sid] = self._foreign_releases.get(sid, 0) + 1

    def _find_cycle(self, start: int, goal: int) -> Optional[List[int]]:
        """Path start -> ... -> goal in the edge set (caller holds _lock);
        combined with the just-added goal -> start edge it is a cycle."""
        work = [(start, [start])]
        seen = {start}
        while work:
            cur, path = work.pop()
            for nxt in self._edges.get(cur, ()):
                if nxt == goal:
                    return path + [goal]
                if nxt not in seen:
                    seen.add(nxt)
                    work.append((nxt, path + [nxt]))
        return None

    # -- queries -----------------------------------------------------------

    def violations(self) -> List[LockOrderViolation]:
        with self._lock:
            return list(self._violations)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._violations.clear()


class ThreadLeakError(RuntimeError):
    """Threads outlived the scope that owned them."""

    def __init__(self, leaked: Sequence[threading.Thread], scope: str) -> None:
        self.leaked = list(leaked)
        names = ", ".join(f"{t.name} (daemon={t.daemon})" for t in self.leaked)
        super().__init__(
            f"{len(self.leaked)} thread(s) leaked from {scope}: {names}"
        )


class ThreadLeakChecker:
    """Assert that threads started inside the block die with it.

    ``watch``: fnmatch patterns of thread names that count as leaks
    (default: the harness's own worker prefix).  Unmatched new threads —
    interpreter pools, grpc/orbax internals — are ignored: they are
    process-lifetime by design and would make the check unusable.
    ``grace``: seconds to wait (joining, after a gc pass to trigger
    ``__del__``-based cleanup) before declaring a leak.
    """

    def __init__(
        self,
        *,
        watch: Sequence[str] = ("dtpu-*",),
        grace: float = 5.0,
        raise_on_leak: bool = True,
        scope: str = "scope",
    ) -> None:
        self.watch = tuple(watch)
        self.grace = grace
        self.raise_on_leak = raise_on_leak
        self.scope = scope
        self.leaked: List[threading.Thread] = []
        self._before: Optional[Tuple[threading.Thread, ...]] = None

    def _new_watched(self, before: Tuple[threading.Thread, ...]) -> List[threading.Thread]:
        return [
            t
            for t in threading.enumerate()
            if t not in before
            and t.is_alive()
            and any(fnmatch.fnmatch(t.name, p) for p in self.watch)
        ]

    def __enter__(self) -> "ThreadLeakChecker":
        self._before = tuple(threading.enumerate())
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        assert self._before is not None
        # a del-based cleanup (un-closed PrefetchingIterator) should count
        # as "died with the scope", not as a leak
        gc.collect()
        deadline = time.monotonic() + self.grace
        leaked = self._new_watched(self._before)
        while leaked and time.monotonic() < deadline:
            for t in leaked:
                t.join(timeout=max(0.0, min(0.2, deadline - time.monotonic())))
            leaked = self._new_watched(self._before)
        self.leaked = leaked
        if not leaked:
            return
        # an in-flight exception takes precedence; don't mask it
        if self.raise_on_leak and exc_type is None:
            raise ThreadLeakError(leaked, self.scope)
        logger.warning(
            "thread sentinel: %d thread(s) leaked from %s: %s",
            len(leaked),
            self.scope,
            ", ".join(t.name for t in leaked),
        )
