"""Runtime sentinels: retrace detection, thread-leak checking, lock-order
tracking.

The static pass (``_ast.py``/``_concurrency.py``) catches what it can
read; these catch what only shows up live:

- **RetraceSentinel** — wraps the pre-jit step functions the Trainer
  installs (``train/_trainer.py`` / ``train/_jit_cache.py``).  jax calls
  the wrapped Python function once per TRACE, so the call count IS the
  compile count for that jitted callable: more than ``allowed`` traces of
  one logical step means the step is retrace-prone (shape-unstable
  batches, python branching on traced values, weak cache keying) and every
  extra trace is a silent full XLA compile eaten by the benchmark.  With
  the jit-reuse cache on, a healthy search stays at one trace per step
  signature — which is exactly what the sentinel asserts.
- **ThreadLeakChecker** — a context manager that snapshots live threads on
  entry and reports threads (matching ``watch`` patterns, default the
  harness's own ``dtpu-*`` workers) still alive on exit.  Tests use it to
  assert scheduler/prefetch workers die with their owners; the supervisor
  (``exec/run_trial.py``) runs trials under it in warn mode when
  ``lint.thread_sentinel`` is set.
- **LockOrderSentinel** — a test-time monkeypatch of ``threading.Lock`` /
  ``threading.RLock`` (and therefore every ``Condition``/``Event`` built
  on them afterwards) that records the process's ACTUAL lock-acquisition
  DAG and reports an inversion the moment an edge closes a cycle — the
  dynamic complement of the static ``lock-order-cycle`` rule, catching the
  dispatch the AST cannot resolve.  ``tests/conftest.py`` exposes it as
  the opt-in ``lock_order`` marker (scheduler, journal/recovery, GC, and
  observability suites run under it).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import gc
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("determined_tpu.lint.runtime")


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TraceRecord:
    """Compile accounting for one wrapped step callable."""

    label: str
    allowed: int
    traces: int = 0
    violations: int = 0


class RetraceSentinel:
    """Registry of wrapped step functions and their trace counts.

    ``wrap`` must be applied to the function BEFORE ``jax.jit``: jit then
    invokes the wrapper exactly once per trace/compile of that callable.
    Thread-safe (concurrent trials trace in parallel).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[int, TraceRecord] = {}
        self._seq = 0
        self._enabled = False

    # -- enablement (config-driven; tests flip it directly) ----------------

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- wrapping ----------------------------------------------------------

    def wrap(
        self, label: str, fn: Callable[..., Any], *, allowed: int = 1
    ) -> Callable[..., Any]:
        """Count executions of ``fn`` (= traces once jitted) under ``label``.

        ``allowed``: traces that are expected for this callable.  One for a
        train step; an eval step legitimately traces twice (the metric
        accumulator starts empty on the first validation batch, populated
        after).
        """
        with self._lock:
            self._seq += 1
            rec = TraceRecord(label=label, allowed=allowed)
            self._records[self._seq] = rec

        @functools.wraps(fn)
        def traced(*args: Any, **kwargs: Any) -> Any:
            with self._lock:
                rec.traces += 1
                over = rec.traces > rec.allowed
                if over:
                    rec.violations += 1
            if over:
                logger.warning(
                    "retrace sentinel: %s traced %d times (allowed %d) — the "
                    "step is recompiling; look for shape-unstable batches, "
                    "python branching on traced values, or hparams that "
                    "should key the jit cache (docs/lint.md)",
                    rec.label,
                    rec.traces,
                    rec.allowed,
                )
            return fn(*args, **kwargs)

        return traced

    # -- queries -----------------------------------------------------------

    def records(self) -> List[TraceRecord]:
        with self._lock:
            return [dataclasses.replace(r) for r in self._records.values()]

    def violations(self) -> Dict[str, int]:
        """label -> excess trace count, only for offenders."""
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._records.values():
                if r.violations:
                    out[r.label] = out.get(r.label, 0) + r.violations
            return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0


_retrace_sentinel = RetraceSentinel()


def get_retrace_sentinel() -> RetraceSentinel:
    """The process-global sentinel (one process = one jit cache = one
    compile ledger)."""
    return _retrace_sentinel


# ---------------------------------------------------------------------------
# thread-leak checker
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# lock-order sentinel
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LockOrderViolation:
    """An observed acquisition-order inversion: taking ``acquired`` while
    holding ``held`` closes a cycle against the edges in ``cycle``."""

    thread: str
    held: str
    acquired: str
    cycle: List[str]

    def format(self) -> str:
        return (
            f"lock-order inversion on thread {self.thread}: acquired "
            f"{self.acquired} while holding {self.held}, but the process "
            f"already acquired them in the opposite order "
            f"(cycle: {' -> '.join(self.cycle)})"
        )


class _TrackedLock:
    """Wrapper a patched ``threading.Lock``/``RLock`` factory returns.

    Delegates everything to the real primitive; ``acquire``/``release``
    additionally maintain the sentinel's per-thread held stack and the
    global acquisition DAG.  ``__getattr__`` forwards the private
    ``_release_save``/``_acquire_restore``/``_is_owned`` trio, so
    ``Condition`` built on a tracked RLock works unchanged (its ``wait``
    then bypasses the bookkeeping — conservative: the lock stays "held"
    on our stack through the wait, which can only ADD ordering edges the
    thread really did establish before waiting).
    """

    def __init__(self, sentinel: "LockOrderSentinel", inner: Any, sid: int,
                 label: str, reentrant: bool) -> None:
        self._dtpu_sentinel = sentinel
        self._dtpu_inner = inner
        self._dtpu_sid = sid
        self._dtpu_label = label
        self._dtpu_reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._dtpu_inner.acquire(blocking, timeout)
        if got:
            self._dtpu_sentinel._note_acquire(self)
        return got

    def release(self) -> None:
        self._dtpu_sentinel._note_release(self)
        self._dtpu_inner.release()

    def locked(self) -> bool:
        return self._dtpu_inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._dtpu_inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<tracked {self._dtpu_label} wrapping {self._dtpu_inner!r}>"


class LockOrderSentinel:
    """Record the live acquisition DAG; flag inversions deterministically.

    ``install()`` patches ``threading.Lock`` and ``threading.RLock`` so
    every lock created AFTERWARDS is tracked (existing locks are not —
    tests construct their subjects inside the sentinel's scope, which the
    conftest ``lock_order`` marker guarantees).  On each acquire with
    other tracked locks held, the edge ``innermost-held -> acquired`` is
    added; an edge that completes a cycle records a
    ``LockOrderViolation`` carrying both directions' witnesses.  The
    check fires on the ORDER, not on an actual deadlock, so the inversion
    is caught even when the interleaving happened to get away with it —
    that is the point: the failure is deterministic where the deadlock is
    a race.

    Locks are labeled by allocation site (``file:line#serial``), which is
    what the violation message shows.  Reentrant re-acquisition of an
    RLock adds no edges.  Not re-entrant itself: one install per process
    at a time (the conftest fixture serializes naturally).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()  # guards graph + labels + handoffs
        self._edges: Dict[int, set] = {}
        self._labels: Dict[int, str] = {}
        self._violations: List[LockOrderViolation] = []
        self._seq = 0
        self._held = threading.local()
        #: sid -> count of releases by threads that never acquired it
        #: (the legal Lock handoff pattern); the acquiring thread purges
        #: its stale stack entry lazily on its next acquire
        self._foreign_releases: Dict[int, int] = {}
        self._orig_lock: Optional[Any] = None
        self._orig_rlock: Optional[Any] = None
        self._installed = False

    # -- patching ----------------------------------------------------------

    def _alloc_site(self) -> str:
        import sys

        f = sys._getframe(2)
        while f is not None and "threading" in (f.f_code.co_filename or ""):
            f = f.f_back
        if f is None:  # pragma: no cover - interpreter internals
            return "<unknown>"
        return f"{f.f_code.co_filename}:{f.f_lineno}"

    def _make_factory(self, orig: Any, reentrant: bool) -> Any:
        def factory(*args: Any, **kwargs: Any) -> _TrackedLock:
            inner = orig(*args, **kwargs)
            with self._lock:
                self._seq += 1
                sid = self._seq
            label = f"{self._alloc_site()}#{sid}"
            with self._lock:
                self._labels[sid] = label
            return _TrackedLock(self, inner, sid, label, reentrant)

        return factory

    def install(self) -> "LockOrderSentinel":
        if self._installed:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        threading.Lock = self._make_factory(self._orig_lock, False)  # type: ignore[misc]
        threading.RLock = self._make_factory(self._orig_rlock, True)  # type: ignore[misc]
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._orig_lock  # type: ignore[misc]
        threading.RLock = self._orig_rlock  # type: ignore[misc]
        self._installed = False

    def __enter__(self) -> "LockOrderSentinel":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    # -- bookkeeping -------------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def _purge_foreign_releases(self, stack: List[int]) -> None:
        """Drop stack entries for locks some OTHER thread has since
        released (acquire-here / release-there is legal for Lock); without
        this the handed-off lock looks held forever and every later
        acquire on this thread grows a phantom ordering edge."""
        if not self._foreign_releases:  # benign unlocked read
            return
        with self._lock:
            for i in range(len(stack) - 1, -1, -1):
                n = self._foreign_releases.get(stack[i], 0)
                if n:
                    sid = stack[i]
                    del stack[i]
                    if n == 1:
                        del self._foreign_releases[sid]
                    else:
                        self._foreign_releases[sid] = n - 1

    def _note_acquire(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        self._purge_foreign_releases(stack)
        sid = lock._dtpu_sid
        if sid in stack:
            # reentrant hold (RLock, or Condition re-entry): no new order
            # information; push so the matching release pops symmetrically
            stack.append(sid)
            return
        if stack:
            holder = stack[-1]
            with self._lock:
                added = sid not in self._edges.setdefault(holder, set())
                if added:
                    self._edges[holder].add(sid)
                    cycle = self._find_cycle(sid, holder)
                    if cycle is not None:
                        self._violations.append(
                            LockOrderViolation(
                                thread=threading.current_thread().name,
                                held=self._labels.get(holder, str(holder)),
                                acquired=self._labels.get(sid, str(sid)),
                                cycle=[
                                    self._labels.get(s, str(s))
                                    for s in [holder, sid] + cycle[1:]
                                ],
                            )
                        )
        stack.append(sid)

    def _note_release(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        sid = lock._dtpu_sid
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == sid:
                del stack[i]
                return
        # released by a thread that never acquired it: cross-thread
        # handoff — the acquirer's stack entry is purged on its next
        # acquire rather than mutated from here (stacks are thread-local)
        with self._lock:
            self._foreign_releases[sid] = self._foreign_releases.get(sid, 0) + 1

    def _find_cycle(self, start: int, goal: int) -> Optional[List[int]]:
        """Path start -> ... -> goal in the edge set (caller holds _lock);
        combined with the just-added goal -> start edge it is a cycle."""
        work = [(start, [start])]
        seen = {start}
        while work:
            cur, path = work.pop()
            for nxt in self._edges.get(cur, ()):
                if nxt == goal:
                    return path + [goal]
                if nxt not in seen:
                    seen.add(nxt)
                    work.append((nxt, path + [nxt]))
        return None

    # -- queries -----------------------------------------------------------

    def violations(self) -> List[LockOrderViolation]:
        with self._lock:
            return list(self._violations)

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._violations.clear()


# ---------------------------------------------------------------------------
# collective-sequence sentinel
# ---------------------------------------------------------------------------


class CollectiveDivergenceError(RuntimeError):
    """Two ranks issued different collective sequences.

    This is the named, located form of the worst debugging experience in
    distributed training: without the sentinel, the divergence is a
    silent hang — every healthy rank blocks inside its collective until
    the 600-second timeout, with no indication of WHICH rank took a
    different path or WHICH op it skipped.  The error names the first
    divergent op and carries both ranks' recent traces.
    """

    def __init__(
        self,
        message: str,
        *,
        op_index: int = -1,
        ranks: Optional[Dict[int, str]] = None,
        traces: Optional[Dict[int, List[str]]] = None,
    ) -> None:
        super().__init__(message)
        #: absolute index (0-based) of the first divergent collective
        self.op_index = op_index
        #: rank -> the op it issued at the divergence point
        self.ranks = dict(ranks or {})
        #: rank -> recent (op, detail) signature trace
        self.traces = dict(traces or {})


#: first element of every enveloped payload — lets the receiving side
#: distinguish "sentinel payload" from "raw payload from a rank that does
#: not have the sentinel installed" (a misconfiguration worth naming)
_CSEQ_MAGIC = "__dtpu_cseq__"


def _payload_sig(obj: Any) -> str:
    """Cheap structural signature of a collective operand: the top-level
    TYPE (plus shape for arrays).  Deliberately shallow and deliberately
    length-free — per-rank operands legitimately differ in content and
    size (``allgather(hostname)``), but a type split (one rank sends a
    tuple, another None) is the wrong-branch signal.  The digest must
    cost nanoseconds; op identity is what diverges first."""
    if obj is None:
        return "none"
    shape = getattr(obj, "shape", None)
    if shape is not None:
        return f"{type(obj).__name__}{tuple(shape)!r}"
    return type(obj).__name__


class _CseqState:
    """Per-DistributedContext rolling digest of the collective sequence."""

    __slots__ = ("rank", "seq", "xchg", "digest", "trace", "lock")

    def __init__(self, rank: int, trace_depth: int) -> None:
        import collections

        self.rank = rank
        self.seq = 0  # collectives recorded so far (exchanged + dispatch-site)
        self.xchg = 0  # EXCHANGED collectives only (the injection counter)
        self.digest = 0  # crc32 chain over every recorded signature
        self.trace = collections.deque(maxlen=trace_depth)
        self.lock = threading.Lock()

    def record(self, sig: str) -> None:
        import zlib

        with self.lock:
            self.seq += 1
            self.digest = zlib.crc32(sig.encode(), self.digest) & 0xFFFFFFFF
            self.trace.append(sig)

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "rank": self.rank,
                "seq": self.seq,
                "digest": self.digest,
                # only the TAIL rides the wire: the digest covers the full
                # history, the shipped tail exists to NAME the divergence
                # point in the error; the deeper local deque stays
                # available to whoever catches the exception
                "trace": list(self.trace)[-8:],
            }


class CollectiveSequenceSentinel:
    """Digest every rank's collective sequence; name divergences.

    ``install()`` patches the control-plane collective entry points on
    ``DistributedContext`` (``allgather``/``gather``/``broadcast``/
    ``barrier`` and their ``_local`` variants) so that every call:

    1. records an ``(op, payload-structure)`` signature into a per-rank
       rolling crc32 digest (``record`` is also public, so un-exchanged
       dispatch sites — the trainer's jitted step, which carries the
       tensor-plane psums — feed the same digest);
    2. piggybacks a tiny envelope ``{rank, seq, digest, op, trace}`` on
       the payload it was going to exchange anyway;
    3. verifies, on receipt, that every participating rank agrees on
       ``(seq, op, digest)`` — raising a deterministic
       ``CollectiveDivergenceError`` naming the first divergent op and
       both ranks' traces the moment the sequences disagree, instead of
       letting the mismatch surface as a 600-second silent hang.

    The exchange rides the collective that was already happening, so the
    sentinel adds no extra round trips; overhead per collective is one
    crc32 of a short string plus a small dict (``DTPU_BENCH_SENTINEL=1``
    in ``bench.py`` tracks the number).  Divergences where one rank calls
    a DIFFERENT op on a compatible transport (allgather vs barrier, the
    common wrong-branch case) are caught in-band; a rank that issues NO
    collective still parks its peers until the control-plane deadline,
    but the deadline's ``PeerLostError`` then names the silent rank.

    Enablement: ``lint.collective_sentinel: true`` in the experiment
    config (the trial entrypoint installs it before ``core.init()``), the
    ``DTPU_COLLECTIVE_SENTINEL=1`` env, or the ``collective_order``
    pytest marker (``tests/conftest.py``).  Must be installed on EVERY
    rank of a gang or none — a raw (non-enveloped) payload from a
    sentinel-less peer raises with a message saying exactly that.

    Fault injection (the devcluster acceptance test): the env
    ``DTPU_CSEQ_INJECT="<rank>:<seq>:<op>"`` makes the named rank
    advertise ``<op>`` as its ``<seq>``-th collective — simulating the
    wrong-branch divergence without hand-writing a divergent trial.
    """

    def __init__(self, *, trace_depth: int = 64) -> None:
        self.trace_depth = trace_depth
        self._installed = False
        self._orig: Dict[str, Any] = {}
        self._violations: List[CollectiveDivergenceError] = []
        self._vlock = threading.Lock()
        # parsed DTPU_CSEQ_INJECT, or None
        self._inject: Optional[Tuple[int, int, str]] = None
        import os

        spec = os.environ.get("DTPU_CSEQ_INJECT", "")
        if spec:
            try:
                r, s, op = spec.split(":", 2)
                self._inject = (int(r), int(s), op)
            except ValueError:
                logger.warning("ignoring malformed DTPU_CSEQ_INJECT=%r", spec)

    @property
    def installed(self) -> bool:
        return self._installed

    # -- state -------------------------------------------------------------

    def _state(self, dist: Any) -> _CseqState:
        st = getattr(dist, "_dtpu_cseq", None)
        if st is None:
            st = _CseqState(getattr(dist, "rank", 0), self.trace_depth)
            dist._dtpu_cseq = st
        return st

    def record(self, dist: Any, op: str, detail: str = "") -> None:
        """Public dispatch-site hook: fold an un-exchanged collective
        (e.g. the jitted train step carrying the gradient psums) into the
        rolling digest.  The mismatch surfaces at the NEXT exchanged
        collective, whose envelope carries the digest."""
        self._state(dist).record(f"{op}({detail})" if detail else op)

    def violations(self) -> List[CollectiveDivergenceError]:
        with self._vlock:
            return list(self._violations)

    def reset(self) -> None:
        with self._vlock:
            self._violations.clear()

    # -- envelope exchange -------------------------------------------------

    def _sig_for(self, st: _CseqState, op: str, obj: Any) -> str:
        # broadcast/gather payloads are one-sided BY DESIGN (chief sends,
        # or each rank contributes local data the chief merges), so only
        # the op identity is digested for them; symmetric exchanges also
        # digest the operand's structural signature
        if op.startswith(("broadcast", "gather")):
            sig = op
        else:
            sig = f"{op}({_payload_sig(obj)})"
        with st.lock:
            st.xchg += 1
            xchg = st.xchg
        if self._inject is not None:
            rank, at_xchg, fake_op = self._inject
            # counted in EXCHANGED collectives (not dispatch-site records),
            # so the injection point is stable regardless of how many step
            # segments the trainer folded in between
            if st.rank == rank and xchg == at_xchg:
                logger.warning(
                    "cseq inject: rank %d advertising %r instead of %r at "
                    "exchanged collective #%d",
                    rank, fake_op, sig, at_xchg,
                )
                return fake_op
        return sig

    def _divergence(
        self, envs: List[Dict[str, Any]]
    ) -> Optional[CollectiveDivergenceError]:
        """Compare all ranks' envelopes; build the named error or None."""
        base = envs[0]
        if all(
            e["seq"] == base["seq"]
            and e["op"] == base["op"]
            and e["digest"] == base["digest"]
            for e in envs[1:]
        ):
            return None
        # find the first divergent absolute op index from the traces
        traces = {e["rank"]: list(e["trace"]) + [e["op"]] for e in envs}
        starts = {e["rank"]: e["seq"] + 1 - len(traces[e["rank"]]) for e in envs}
        first = min(starts.values())
        last = max(e["seq"] for e in envs)
        op_index = -1
        at: Dict[int, str] = {}
        for i in range(max(first, 0), last + 1):
            ops = {
                r: traces[r][i - starts[r]]
                for r in traces
                if 0 <= i - starts[r] < len(traces[r])
            }
            if len(set(ops.values())) > 1 or (envs and len(ops) < len(envs)):
                op_index = i
                at = {r: ops.get(r, "<nothing>") for r in traces}
                break
        if op_index < 0:
            # identical visible traces but different digests: the split is
            # older than the rolling window
            op_index = first
            at = {e["rank"]: "<diverged before trace window>" for e in envs}
        who = ", ".join(f"rank {r} issued `{op}`" for r, op in sorted(at.items()))
        err = CollectiveDivergenceError(
            f"collective sequence diverged at op #{op_index + 1}: {who}. "
            "One rank took a different code path; without this sentinel "
            "every healthy rank would hang in its collective to the "
            f"timeout. Recent traces: "
            + "; ".join(
                f"rank {r}: {tr[-8:]}" for r, tr in sorted(traces.items())
            ),
            op_index=op_index,
            ranks=at,
            traces=traces,
        )
        return err

    def _raise(self, err: CollectiveDivergenceError) -> None:
        with self._vlock:
            self._violations.append(err)
        raise err

    def _unwrap(self, item: Any) -> Tuple[Dict[str, Any], Any]:
        if (
            isinstance(item, tuple)
            and len(item) == 3
            and item[0] == _CSEQ_MAGIC
            and isinstance(item[1], dict)
        ):
            return item[1], item[2]
        raise CollectiveDivergenceError(
            "collective-sequence sentinel received a raw (non-enveloped) "
            "payload: a peer rank is running WITHOUT the sentinel. Enable "
            "it on every rank of the gang (DTPU_COLLECTIVE_SENTINEL=1 / "
            "lint.collective_sentinel) or on none."
        )

    # -- patched entry points ----------------------------------------------

    def _solo(self, dist: Any, op: str) -> bool:
        """Single-participant group: record the op (the sequence ledger
        stays complete) but skip the envelope — there is no peer to
        verify against, and Dummy contexts sit on every local-experiment
        hot path."""
        size = dist.local_size if op.endswith("_local") else dist.size
        return size <= 1

    def _exchange_allgather(
        self, dist: Any, obj: Any, op: str, orig: Any
    ) -> List[Any]:
        st = self._state(dist)
        sig = self._sig_for(st, op, obj)
        env = st.snapshot()
        env["op"] = sig
        st.record(sig)
        if self._solo(dist, op):
            return orig(dist, obj)
        result = orig(dist, (_CSEQ_MAGIC, env, obj))
        pairs = [self._unwrap(r) for r in result]
        err = self._divergence([p[0] for p in pairs])
        if err is not None:
            self._raise(err)
        return [p[1] for p in pairs]

    def _exchange_gather(
        self, dist: Any, obj: Any, op: str, orig: Any
    ) -> Optional[List[Any]]:
        st = self._state(dist)
        sig = self._sig_for(st, op, obj)
        env = st.snapshot()
        env["op"] = sig
        st.record(sig)
        if self._solo(dist, op):
            return orig(dist, obj)
        result = orig(dist, (_CSEQ_MAGIC, env, obj))
        if result is None:
            return None  # worker side: the chief verifies
        pairs = [self._unwrap(r) for r in result]
        err = self._divergence([p[0] for p in pairs])
        if err is not None:
            self._raise(err)
        return [p[1] for p in pairs]

    def _exchange_broadcast(self, dist: Any, obj: Any, op: str, orig: Any) -> Any:
        st = self._state(dist)
        sig = self._sig_for(st, op, obj)
        env = st.snapshot()
        env["op"] = sig
        st.record(sig)
        if self._solo(dist, op):
            return orig(dist, obj)
        result = orig(dist, (_CSEQ_MAGIC, env, obj))
        peer_env, payload = self._unwrap(result)
        # one-sided verification: each receiver compares the chief's
        # envelope against its OWN expected position
        if (
            peer_env["seq"] != env["seq"]
            or peer_env["op"] != env["op"]
            or peer_env["digest"] != env["digest"]
        ):
            err = self._divergence([env, peer_env])
            if err is not None:
                self._raise(err)
        return payload

    def install(self) -> "CollectiveSequenceSentinel":
        if self._installed:
            return self
        from determined_tpu.core._distributed import DistributedContext

        sentinel = self
        orig = {
            "allgather": DistributedContext.allgather,
            "allgather_local": DistributedContext.allgather_local,
            "gather": DistributedContext.gather,
            "gather_local": DistributedContext.gather_local,
            "broadcast": DistributedContext.broadcast,
            "broadcast_local": DistributedContext.broadcast_local,
            "barrier": DistributedContext.barrier,
        }
        self._orig = orig

        def allgather(self, obj):
            return sentinel._exchange_allgather(self, obj, "allgather", orig["allgather"])

        def allgather_local(self, obj):
            return sentinel._exchange_allgather(
                self, obj, "allgather_local", orig["allgather_local"]
            )

        def gather(self, obj):
            return sentinel._exchange_gather(self, obj, "gather", orig["gather"])

        def gather_local(self, obj):
            return sentinel._exchange_gather(
                self, obj, "gather_local", orig["gather_local"]
            )

        def broadcast(self, obj=None):
            return sentinel._exchange_broadcast(
                self, obj, "broadcast", orig["broadcast"]
            )

        def broadcast_local(self, obj=None):
            return sentinel._exchange_broadcast(
                self, obj, "broadcast_local", orig["broadcast_local"]
            )

        def barrier(self):
            # route the barrier through the verified allgather so it gets
            # the full both-directions check (it IS an allgather(None))
            sentinel._exchange_allgather(self, None, "barrier", orig["allgather"])

        DistributedContext.allgather = allgather
        DistributedContext.allgather_local = allgather_local
        DistributedContext.gather = gather
        DistributedContext.gather_local = gather_local
        DistributedContext.broadcast = broadcast
        DistributedContext.broadcast_local = broadcast_local
        DistributedContext.barrier = barrier
        self._installed = True
        logger.info("collective-sequence sentinel installed")
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        from determined_tpu.core._distributed import DistributedContext

        for name, fn in self._orig.items():
            setattr(DistributedContext, name, fn)
        self._installed = False

    def __enter__(self) -> "CollectiveSequenceSentinel":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()


_collective_sentinel: Optional[CollectiveSequenceSentinel] = None


def get_collective_sentinel() -> CollectiveSequenceSentinel:
    """Process-global sentinel (one process = one rank = one sequence)."""
    global _collective_sentinel
    if _collective_sentinel is None:
        _collective_sentinel = CollectiveSequenceSentinel()
    return _collective_sentinel


class ThreadLeakError(RuntimeError):
    """Threads outlived the scope that owned them."""

    def __init__(self, leaked: Sequence[threading.Thread], scope: str) -> None:
        self.leaked = list(leaked)
        names = ", ".join(f"{t.name} (daemon={t.daemon})" for t in self.leaked)
        super().__init__(
            f"{len(self.leaked)} thread(s) leaked from {scope}: {names}"
        )


class ThreadLeakChecker:
    """Assert that threads started inside the block die with it.

    ``watch``: fnmatch patterns of thread names that count as leaks
    (default: the harness's own worker prefix).  Unmatched new threads —
    interpreter pools, grpc/orbax internals — are ignored: they are
    process-lifetime by design and would make the check unusable.
    ``grace``: seconds to wait (joining, after a gc pass to trigger
    ``__del__``-based cleanup) before declaring a leak.
    """

    def __init__(
        self,
        *,
        watch: Sequence[str] = ("dtpu-*",),
        grace: float = 5.0,
        raise_on_leak: bool = True,
        scope: str = "scope",
    ) -> None:
        self.watch = tuple(watch)
        self.grace = grace
        self.raise_on_leak = raise_on_leak
        self.scope = scope
        self.leaked: List[threading.Thread] = []
        self._before: Optional[Tuple[threading.Thread, ...]] = None

    def _new_watched(self, before: Tuple[threading.Thread, ...]) -> List[threading.Thread]:
        return [
            t
            for t in threading.enumerate()
            if t not in before
            and t.is_alive()
            and any(fnmatch.fnmatch(t.name, p) for p in self.watch)
        ]

    def __enter__(self) -> "ThreadLeakChecker":
        self._before = tuple(threading.enumerate())
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        assert self._before is not None
        # a del-based cleanup (un-closed PrefetchingIterator) should count
        # as "died with the scope", not as a leak
        gc.collect()
        deadline = time.monotonic() + self.grace
        leaked = self._new_watched(self._before)
        while leaked and time.monotonic() < deadline:
            for t in leaked:
                t.join(timeout=max(0.0, min(0.2, deadline - time.monotonic())))
            leaked = self._new_watched(self._before)
        self.leaked = leaked
        if not leaked:
            return
        # an in-flight exception takes precedence; don't mask it
        if self.raise_on_leak and exc_type is None:
            raise ThreadLeakError(leaked, self.scope)
        logger.warning(
            "thread sentinel: %d thread(s) leaked from %s: %s",
            len(leaked),
            self.scope,
            ", ".join(t.name for t in leaked),
        )
