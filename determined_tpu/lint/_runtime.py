"""Runtime sentinels: retrace detection + thread-leak checking.

The static pass (``_ast.py``) catches what it can read; these two catch
what only shows up live:

- **RetraceSentinel** — wraps the pre-jit step functions the Trainer
  installs (``train/_trainer.py`` / ``train/_jit_cache.py``).  jax calls
  the wrapped Python function once per TRACE, so the call count IS the
  compile count for that jitted callable: more than ``allowed`` traces of
  one logical step means the step is retrace-prone (shape-unstable
  batches, python branching on traced values, weak cache keying) and every
  extra trace is a silent full XLA compile eaten by the benchmark.  With
  the jit-reuse cache on, a healthy search stays at one trace per step
  signature — which is exactly what the sentinel asserts.
- **ThreadLeakChecker** — a context manager that snapshots live threads on
  entry and reports threads (matching ``watch`` patterns, default the
  harness's own ``dtpu-*`` workers) still alive on exit.  Tests use it to
  assert scheduler/prefetch workers die with their owners; the supervisor
  (``exec/run_trial.py``) runs trials under it in warn mode when
  ``lint.thread_sentinel`` is set.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import gc
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("determined_tpu.lint.runtime")


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TraceRecord:
    """Compile accounting for one wrapped step callable."""

    label: str
    allowed: int
    traces: int = 0
    violations: int = 0


class RetraceSentinel:
    """Registry of wrapped step functions and their trace counts.

    ``wrap`` must be applied to the function BEFORE ``jax.jit``: jit then
    invokes the wrapper exactly once per trace/compile of that callable.
    Thread-safe (concurrent trials trace in parallel).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[int, TraceRecord] = {}
        self._seq = 0
        self._enabled = False

    # -- enablement (config-driven; tests flip it directly) ----------------

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- wrapping ----------------------------------------------------------

    def wrap(
        self, label: str, fn: Callable[..., Any], *, allowed: int = 1
    ) -> Callable[..., Any]:
        """Count executions of ``fn`` (= traces once jitted) under ``label``.

        ``allowed``: traces that are expected for this callable.  One for a
        train step; an eval step legitimately traces twice (the metric
        accumulator starts empty on the first validation batch, populated
        after).
        """
        with self._lock:
            self._seq += 1
            rec = TraceRecord(label=label, allowed=allowed)
            self._records[self._seq] = rec

        @functools.wraps(fn)
        def traced(*args: Any, **kwargs: Any) -> Any:
            with self._lock:
                rec.traces += 1
                over = rec.traces > rec.allowed
                if over:
                    rec.violations += 1
            if over:
                logger.warning(
                    "retrace sentinel: %s traced %d times (allowed %d) — the "
                    "step is recompiling; look for shape-unstable batches, "
                    "python branching on traced values, or hparams that "
                    "should key the jit cache (docs/lint.md)",
                    rec.label,
                    rec.traces,
                    rec.allowed,
                )
            return fn(*args, **kwargs)

        return traced

    # -- queries -----------------------------------------------------------

    def records(self) -> List[TraceRecord]:
        with self._lock:
            return [dataclasses.replace(r) for r in self._records.values()]

    def violations(self) -> Dict[str, int]:
        """label -> excess trace count, only for offenders."""
        with self._lock:
            out: Dict[str, int] = {}
            for r in self._records.values():
                if r.violations:
                    out[r.label] = out.get(r.label, 0) + r.violations
            return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0


_retrace_sentinel = RetraceSentinel()


def get_retrace_sentinel() -> RetraceSentinel:
    """The process-global sentinel (one process = one jit cache = one
    compile ledger)."""
    return _retrace_sentinel


# ---------------------------------------------------------------------------
# thread-leak checker
# ---------------------------------------------------------------------------


class ThreadLeakError(RuntimeError):
    """Threads outlived the scope that owned them."""

    def __init__(self, leaked: Sequence[threading.Thread], scope: str) -> None:
        self.leaked = list(leaked)
        names = ", ".join(f"{t.name} (daemon={t.daemon})" for t in self.leaked)
        super().__init__(
            f"{len(self.leaked)} thread(s) leaked from {scope}: {names}"
        )


class ThreadLeakChecker:
    """Assert that threads started inside the block die with it.

    ``watch``: fnmatch patterns of thread names that count as leaks
    (default: the harness's own worker prefix).  Unmatched new threads —
    interpreter pools, grpc/orbax internals — are ignored: they are
    process-lifetime by design and would make the check unusable.
    ``grace``: seconds to wait (joining, after a gc pass to trigger
    ``__del__``-based cleanup) before declaring a leak.
    """

    def __init__(
        self,
        *,
        watch: Sequence[str] = ("dtpu-*",),
        grace: float = 5.0,
        raise_on_leak: bool = True,
        scope: str = "scope",
    ) -> None:
        self.watch = tuple(watch)
        self.grace = grace
        self.raise_on_leak = raise_on_leak
        self.scope = scope
        self.leaked: List[threading.Thread] = []
        self._before: Optional[Tuple[threading.Thread, ...]] = None

    def _new_watched(self, before: Tuple[threading.Thread, ...]) -> List[threading.Thread]:
        return [
            t
            for t in threading.enumerate()
            if t not in before
            and t.is_alive()
            and any(fnmatch.fnmatch(t.name, p) for p in self.watch)
        ]

    def __enter__(self) -> "ThreadLeakChecker":
        self._before = tuple(threading.enumerate())
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> None:
        assert self._before is not None
        # a del-based cleanup (un-closed PrefetchingIterator) should count
        # as "died with the scope", not as a leak
        gc.collect()
        deadline = time.monotonic() + self.grace
        leaked = self._new_watched(self._before)
        while leaked and time.monotonic() < deadline:
            for t in leaked:
                t.join(timeout=max(0.0, min(0.2, deadline - time.monotonic())))
            leaked = self._new_watched(self._before)
        self.leaked = leaked
        if not leaked:
            return
        # an in-flight exception takes precedence; don't mask it
        if self.raise_on_leak and exc_type is None:
            raise ThreadLeakError(leaked, self.scope)
        logger.warning(
            "thread sentinel: %d thread(s) leaked from %s: %s",
            len(leaked),
            self.scope,
            ", ".join(t.name for t in leaked),
        )
