"""Cross-module concurrency analysis: lock graphs, blocking-under-lock,
signal-handler safety.

This is the whole-program half of the preflight analyzer.  The per-module
walker (``_ast.py``) sees one file at a time; a lock-order inversion is a
property of the PROGRAM — the ``searcher -> journal`` invariant involves a
lock in ``searcher/_searcher.py`` and one in ``experiment/journal.py``,
connected by calls in ``experiment/local.py``.  So this pass:

1. indexes every module in the lint target: lock objects (``threading.Lock
   / RLock / Condition / Semaphore`` bound in ``__init__``, at module
   scope, or to function locals — the same ctor inference
   ``unlocked-shared-state`` uses), class attribute types
   (``self._journal = ExperimentJournal(...)``), imports, methods, nested
   functions, and ``signal.signal`` registrations;
2. resolves ``with lock:`` regions and the calls made inside them ACROSS
   module boundaries (``self.method``, ``self.attr.method`` via the
   attr-type map, module functions, ``from x import y`` / ``import x.y``
   targets, base-class methods), building a lock-acquisition graph whose
   edges carry witness call chains;
3. reports cycles in that graph (``lock-order-cycle``), blocking calls
   reached while a lock is held (``blocking-under-lock``, one diagnostic
   per site with the held-lock chain), and signal handlers whose bodies
   go beyond the flag-set pattern (``signal-handler-unsafe``).

Resolution is deliberately conservative: a call the index cannot resolve
contributes nothing (no guessed edges, no guessed blocking), so every
finding names a concrete path.  The runtime ``LockOrderSentinel``
(``_runtime.py``) covers the dynamic dispatch this pass cannot see.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from determined_tpu.lint._ast import dotted_name, parse_suppressions
from determined_tpu.lint._diag import Diagnostic

#: ctor basenames -> primitive kind (lock kinds participate in the graph)
_LOCK_CTORS = {
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
    "Semaphore": "Semaphore",
    "BoundedSemaphore": "Semaphore",
}
_QUEUE_CTORS = frozenset({"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"})
_EVENT_CTORS = frozenset({"Event"})
_THREAD_CTORS = frozenset({"Thread", "Timer"})

#: calls that block by dotted name (exact match)
_BLOCKING_CALLS = {
    "os.fsync": "fsync",
    "os.replace": "atomic-replace",
    "os.rename": "atomic-replace",
    "shutil.rmtree": "tree-io",
    "shutil.copytree": "tree-io",
    "shutil.move": "tree-io",
    "time.sleep": "sleep",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "jax.device_get": "device-sync",
    "jax.block_until_ready": "device-sync",
}
#: calls that block when the callable's LAST name segment contains the key
#: (catches wrappers like ``_tls_urlopen`` alongside ``urllib.request.urlopen``)
_BLOCKING_LAST_SEGMENT = {
    "block_until_ready": "device-sync",
    "urlopen": "net-io",
    "getresponse": "net-io",
}
_REQUESTS_METHODS = frozenset(
    {"get", "post", "put", "delete", "head", "patch", "request", "send"}
)
_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}
)
#: handler calls that ARE the flag-set pattern (async-signal-tolerable)
_HANDLER_SAFE_CALLS = frozenset(
    {
        "os.write",
        "os.kill",
        "os._exit",
        "sys.exit",
        "signal.signal",
        "signal.getsignal",
        "callable",
        "getattr",
        "setattr",
        "list",
        "dict",
        "tuple",
    }
)

_MAX_CALL_DEPTH = 8


class LockDef:
    """One lock object, identified by where it is BOUND (not where it is
    used), so every use site across modules maps to the same node."""

    def __init__(self, lock_id: str, kind: str, module: str, line: int) -> None:
        self.id = lock_id
        self.kind = kind  # Lock | RLock | Condition | Semaphore
        self.module = module
        self.line = line

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"LockDef({self.id})"


class FuncInfo:
    """One function/method (including nested defs) with its lexical home."""

    def __init__(
        self,
        qname: str,
        node: ast.AST,
        module: "ModuleInfo",
        cls: Optional["ClassInfo"],
        parent: Optional["FuncInfo"],
    ) -> None:
        self.qname = qname
        self.node = node
        self.module = module
        self.cls = cls
        self.parent = parent
        self.children: Dict[str, "FuncInfo"] = {}
        #: locals bound to sync primitives / threads inside this function
        self.local_kinds: Dict[str, str] = {}
        self.local_locks: Dict[str, LockDef] = {}


class ClassInfo:
    def __init__(self, name: str, node: ast.ClassDef, module: "ModuleInfo") -> None:
        self.name = name
        self.node = node
        self.module = module
        self.bases: List[str] = [
            b for b in (dotted_name(base) for base in node.bases) if b
        ]
        self.methods: Dict[str, FuncInfo] = {}
        self.lock_attrs: Dict[str, LockDef] = {}
        #: self.<attr> -> primitive kind ("Queue"/"Event"/"Thread"/...)
        self.attr_kinds: Dict[str, str] = {}
        #: self.<attr> -> dotted ctor name, for cross-class call resolution
        self.attr_ctors: Dict[str, str] = {}


class ModuleInfo:
    def __init__(self, name: str, filename: str, source: str, tree: ast.Module) -> None:
        self.name = name
        self.filename = filename
        self.source = source
        self.tree = tree
        self.line_offset = 0
        self.suppressions = parse_suppressions(source)
        #: local alias -> imported module name (``import x.y as z``)
        self.imports: Dict[str, str] = {}
        #: local name -> (from-module, original name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.module_locks: Dict[str, LockDef] = {}
        self.module_kinds: Dict[str, str] = {}
        #: (call node, handler expr, enclosing FuncInfo or None)
        self.signal_registrations: List[
            Tuple[ast.Call, ast.AST, Optional[FuncInfo]]
        ] = []


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """Primitive kind for ``<name> = <Ctor>(...)`` assignments."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if not name:
        return None
    base = name.split(".")[-1]
    if base in _LOCK_CTORS:
        return _LOCK_CTORS[base]
    if base in _QUEUE_CTORS:
        return "Queue"
    if base in _EVENT_CTORS:
        return "Event"
    if base in _THREAD_CTORS:
        return "Thread"
    return None


def _annotation_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Dotted class name out of an attribute annotation, unwrapping
    ``Optional[...]``-style typing wrappers — ``self.journal:
    Optional[ExperimentJournal] = None`` types the attr for call
    resolution even though its ctor runs later."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value)
        if base and base.split(".")[-1] in ("Optional", "Final", "ClassVar"):
            return _annotation_class(ann.slice)
        return None
    name = dotted_name(ann)
    if name and name.split(".")[-1] not in ("Any", "None", "object"):
        return name
    return None


def _walk_pruning_defs(root: ast.AST):
    """``ast.walk`` minus nested function/lambda SUBTREES (the root may
    itself be a def — only NESTED defs are pruned).  ``ast.walk`` with an
    isinstance-``continue`` only skips the def node itself and still
    yields its children, so a name rebound inside a nested def would leak
    into the enclosing scope's bindings (splitting one lock into two
    graph identities)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        if node is not root and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _assign_pairs(stmt: ast.stmt) -> List[Tuple[ast.AST, ast.AST]]:
    """(target, value) pairs for Assign/AnnAssign statements."""
    if isinstance(stmt, ast.Assign) and stmt.value is not None:
        return [(t, stmt.value) for t in stmt.targets]
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [(stmt.target, stmt.value)]
    return []


# ---------------------------------------------------------------------------
# index construction
# ---------------------------------------------------------------------------


class _ModuleIndexer(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo) -> None:
        self.mod = mod
        self._cls: Optional[ClassInfo] = None
        self._fn: Optional[FuncInfo] = None

    # -- imports (collected at any depth: local imports resolve calls too)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.asname:
                self.mod.imports[alias.asname] = alias.name
            else:
                top = alias.name.split(".")[0]
                self.mod.imports[top] = top

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is None or node.level:
            return  # relative imports: not used in this codebase
        for alias in node.names:
            self.mod.from_imports[alias.asname or alias.name] = (
                node.module,
                alias.name,
            )

    # -- scopes

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self._cls is not None or self._fn is not None:
            return  # nested classes: out of scope
        cls = ClassInfo(node.name, node, self.mod)
        self.mod.classes[node.name] = cls
        self._cls = cls
        for stmt in node.body:
            self.visit(stmt)
        self._cls = None

    def _visit_fn(self, node: ast.AST) -> None:
        name = getattr(node, "name", "<lambda>")
        if self._fn is not None:
            qname = f"{self._fn.qname}.{name}"
            fn = FuncInfo(qname, node, self.mod, self._fn.cls, self._fn)
            self._fn.children[name] = fn
        elif self._cls is not None:
            qname = f"{self.mod.name}:{self._cls.name}.{name}"
            fn = FuncInfo(qname, node, self.mod, self._cls, None)
            self._cls.methods[name] = fn
        else:
            qname = f"{self.mod.name}:{name}"
            fn = FuncInfo(qname, node, self.mod, None, None)
            self.mod.functions[name] = fn

        if self._cls is not None and name == "__init__" and self._fn is None:
            self._scan_init(node, self._cls)
        self._scan_locals(node, fn)

        prev, self._fn = self._fn, fn
        for stmt in getattr(node, "body", []):
            self.visit(stmt)
        self._fn = prev

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _scan_init(self, init: ast.AST, cls: ClassInfo) -> None:
        for stmt in _walk_pruning_defs(init):
            for target, value in _assign_pairs(stmt):
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                kind = _ctor_kind(value)
                if kind in _LOCK_CTORS.values():
                    cls.lock_attrs[target.attr] = LockDef(
                        f"{self.mod.name}:{cls.name}.{target.attr}",
                        kind,
                        self.mod.name,
                        getattr(stmt, "lineno", 1),
                    )
                elif kind is not None:
                    cls.attr_kinds[target.attr] = kind
                elif isinstance(value, ast.Call):
                    ctor = dotted_name(value.func)
                    if ctor:
                        cls.attr_ctors[target.attr] = ctor
            # annotation-only / None-initialized attrs: the annotation is
            # the only type evidence (`self.journal: Optional[Journal]`)
            if (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Attribute)
                and isinstance(stmt.target.value, ast.Name)
                and stmt.target.value.id == "self"
                and stmt.target.attr not in cls.attr_ctors
                and stmt.target.attr not in cls.lock_attrs
                and _ctor_kind(stmt.value) is None
            ):
                ctor = _annotation_class(stmt.annotation)
                if ctor:
                    cls.attr_ctors[stmt.target.attr] = ctor

    def _scan_locals(self, node: ast.AST, fn: FuncInfo) -> None:
        """Function-local primitive bindings (NOT descending into nested
        defs — those get their own FuncInfo; closures look upward)."""
        for sub in _walk_pruning_defs(node):
            for target, value in _assign_pairs(sub):
                if not isinstance(target, ast.Name):
                    continue
                kind = _ctor_kind(value)
                if kind in _LOCK_CTORS.values():
                    fn.local_locks[target.id] = LockDef(
                        f"{fn.qname}.{target.id}",
                        kind,
                        self.mod.name,
                        getattr(sub, "lineno", 1),
                    )
                elif kind is not None:
                    fn.local_kinds[target.id] = kind

    # -- module-level locks + signal registrations

    def visit_Assign(self, node: ast.Assign) -> None:
        self._module_binding(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._module_binding(node)
        self.generic_visit(node)

    def _module_binding(self, node: ast.stmt) -> None:
        if self._cls is not None or self._fn is not None:
            return
        for target, value in _assign_pairs(node):
            if not isinstance(target, ast.Name):
                continue
            kind = _ctor_kind(value)
            if kind in _LOCK_CTORS.values():
                self.mod.module_locks[target.id] = LockDef(
                    f"{self.mod.name}:{target.id}",
                    kind,
                    self.mod.name,
                    getattr(node, "lineno", 1),
                )
            elif kind is not None:
                self.mod.module_kinds[target.id] = kind

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name and name.split(".")[-1] == "signal" and len(node.args) >= 2:
            base = name.rsplit(".", 1)[0] if "." in name else ""
            if base in ("signal", "", "_signal"):
                self.mod.signal_registrations.append(
                    (node, node.args[1], self._fn)
                )
        self.generic_visit(node)


def _module_name_for(path: str) -> str:
    """Dotted module name by walking up through ``__init__.py`` packages;
    plain scripts keep their stem (they import the package, never the
    reverse — ``ProgramIndex.add_source`` de-collides stems that repeat)."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


# ---------------------------------------------------------------------------
# program index + resolution
# ---------------------------------------------------------------------------


class ProgramIndex:
    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_file: Dict[str, ModuleInfo] = {}

    def add_source(
        self, filename: str, source: str, *, line_offset: int = 0
    ) -> Optional[ModuleInfo]:
        try:
            tree = ast.parse(source, filename=filename)
        except SyntaxError:
            return None  # the per-module pass already reports parse errors
        name = _module_name_for(filename)
        if name in self.modules and self.modules[name].filename != filename:
            # plain scripts can share a stem (examples/*/model_def.py);
            # each must stay in the index — they are never import targets,
            # so a mangled key loses no resolution, only collisions
            serial = 2
            while f"{name}~{serial}" in self.modules:
                serial += 1
            name = f"{name}~{serial}"
        mod = ModuleInfo(name, filename, source, tree)
        mod.line_offset = line_offset
        _ModuleIndexer(mod).visit(tree)
        self.modules[mod.name] = mod
        self.by_file[filename] = mod
        return mod

    # -- name resolution ---------------------------------------------------

    def resolve_module(self, mod: ModuleInfo, alias: str) -> Optional[ModuleInfo]:
        if alias in mod.imports:
            return self.modules.get(mod.imports[alias])
        if alias in mod.from_imports:
            base, orig = mod.from_imports[alias]
            # ``from pkg import submodule`` imports a MODULE object
            return self.modules.get(f"{base}.{orig}")
        return None

    def resolve_class(self, mod: ModuleInfo, dotted: str) -> Optional[ClassInfo]:
        parts = dotted.split(".")
        if len(parts) == 1:
            name = parts[0]
            if name in mod.classes:
                return mod.classes[name]
            if name in mod.from_imports:
                base, orig = mod.from_imports[name]
                target = self.modules.get(base)
                if target is not None and orig in target.classes:
                    return target.classes[orig]
                # ``from pkg import Name`` re-exported through __init__
                for cand in self.modules.values():
                    if cand.name.startswith(base + ".") and orig in cand.classes:
                        return cand.classes[orig]
            return None
        owner = self.resolve_module(mod, parts[0])
        if owner is not None and len(parts) == 2:
            return owner.classes.get(parts[1])
        return None

    def class_lock_attr(self, cls: ClassInfo, attr: str) -> Optional[LockDef]:
        """Lock attr on the class or a resolvable base (JournaledSearcher
        uses the RLock its Searcher base binds)."""
        seen: Set[str] = set()
        work = [cls]
        while work:
            c = work.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.lock_attrs:
                return c.lock_attrs[attr]
            for base in c.bases:
                b = self.resolve_class(c.module, base)
                if b is not None:
                    work.append(b)
        return None

    def class_attr_kind(self, cls: ClassInfo, attr: str) -> Optional[str]:
        seen: Set[str] = set()
        work = [cls]
        while work:
            c = work.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            if attr in c.attr_kinds:
                return c.attr_kinds[attr]
            for base in c.bases:
                b = self.resolve_class(c.module, base)
                if b is not None:
                    work.append(b)
        return None

    def class_method(self, cls: ClassInfo, name: str) -> Optional[FuncInfo]:
        seen: Set[str] = set()
        work = [cls]
        while work:
            c = work.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            if name in c.methods:
                return c.methods[name]
            for base in c.bases:
                b = self.resolve_class(c.module, base)
                if b is not None:
                    work.append(b)
        return None

    def resolve_lock(self, fn: FuncInfo, expr: ast.AST) -> Optional[LockDef]:
        """LockDef for a ``with``-item / ``.acquire()`` receiver, or None."""
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and fn.cls is not None:
            return self.class_lock_attr(fn.cls, parts[1])
        if len(parts) == 1:
            scope: Optional[FuncInfo] = fn
            while scope is not None:  # closure: locks bound in outer defs
                if parts[0] in scope.local_locks:
                    return scope.local_locks[parts[0]]
                scope = scope.parent
            return fn.module.module_locks.get(parts[0])
        if len(parts) == 2:
            owner = self.resolve_module(fn.module, parts[0])
            if owner is not None:
                return owner.module_locks.get(parts[1])
        return None

    def receiver_kind(self, fn: FuncInfo, expr: ast.AST) -> Optional[str]:
        """Primitive kind of a method-call receiver (Queue/Event/Thread/
        lock kinds), resolved through self attrs, locals, closures, and
        module bindings."""
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and fn.cls is not None:
            lock = self.class_lock_attr(fn.cls, parts[1])
            if lock is not None:
                return lock.kind
            return self.class_attr_kind(fn.cls, parts[1])
        if len(parts) == 1:
            scope: Optional[FuncInfo] = fn
            while scope is not None:
                if parts[0] in scope.local_locks:
                    return scope.local_locks[parts[0]].kind
                if parts[0] in scope.local_kinds:
                    return scope.local_kinds[parts[0]]
                scope = scope.parent
            if parts[0] in fn.module.module_locks:
                return fn.module.module_locks[parts[0]].kind
            return fn.module.module_kinds.get(parts[0])
        return None

    def resolve_call(self, fn: FuncInfo, node: ast.Call) -> Optional[FuncInfo]:
        """Callee FuncInfo for a call expression, or None (conservative)."""
        func = node.func
        name = dotted_name(func)
        if not name:
            return None
        parts = name.split(".")
        # self.method() / self.attr.method()
        if parts[0] == "self" and fn.cls is not None:
            if len(parts) == 2:
                return self.class_method(fn.cls, parts[1])
            if len(parts) == 3:
                ctor = fn.cls.attr_ctors.get(parts[1])
                if ctor:
                    target = self.resolve_class(fn.module, ctor)
                    if target is not None:
                        return self.class_method(target, parts[2])
            return None
        if len(parts) == 1:
            scope: Optional[FuncInfo] = fn
            while scope is not None:  # nested defs call siblings/outer
                if parts[0] in scope.children:
                    return scope.children[parts[0]]
                scope = scope.parent
            if parts[0] in fn.module.functions:
                return fn.module.functions[parts[0]]
            if parts[0] in fn.module.from_imports:
                base, orig = fn.module.from_imports[parts[0]]
                target = self.modules.get(base)
                if target is not None:
                    if orig in target.functions:
                        return target.functions[orig]
                    if orig in target.classes:  # Ctor() runs __init__
                        return target.classes[orig].methods.get("__init__")
            if parts[0] in fn.module.classes:
                return fn.module.classes[parts[0]].methods.get("__init__")
            return None
        if len(parts) == 2:
            owner = self.resolve_module(fn.module, parts[0])
            if owner is not None:
                if parts[1] in owner.functions:
                    return owner.functions[parts[1]]
                if parts[1] in owner.classes:
                    return owner.classes[parts[1]].methods.get("__init__")
        return None


# ---------------------------------------------------------------------------
# per-function event extraction + transitive summaries
# ---------------------------------------------------------------------------


class _Event:
    """One acquire / blocking-call / resolvable-call inside a function,
    with the locks lexically held at that point IN THIS FUNCTION."""

    __slots__ = (
        "kind", "node", "held", "lock", "category", "label", "callee", "exempt",
    )

    def __init__(self, kind: str, node: ast.AST, held: Tuple[LockDef, ...]) -> None:
        self.kind = kind  # "acquire" | "blocking" | "call"
        self.node = node
        self.held = held
        self.lock: Optional[LockDef] = None
        self.category = ""
        self.label = ""
        self.callee: Optional[FuncInfo] = None
        #: excluded from the direct per-site report (e.g. the CV-wait
        #: idiom) but still visible to transitive summaries
        self.exempt = False


def _blocking_category(
    index: ProgramIndex, fn: FuncInfo, node: ast.Call
) -> Optional[Tuple[str, str]]:
    """(category, label) when this call blocks, else None."""
    name = dotted_name(node.func)
    if not name:
        return None
    if name in _BLOCKING_CALLS:
        return _BLOCKING_CALLS[name], name
    last = name.split(".")[-1]
    for needle, cat in _BLOCKING_LAST_SEGMENT.items():
        if needle in last:
            return cat, name
    parts = name.split(".")
    if parts[0] == "requests" and len(parts) == 2 and parts[1] in _REQUESTS_METHODS:
        return "net-io", name
    if len(parts) >= 2 and isinstance(node.func, ast.Attribute):
        attr = parts[-1]
        recv = node.func.value
        kind = index.receiver_kind(fn, recv)
        if attr in ("get", "put") and kind == "Queue":
            for kw in node.keywords:
                if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return None
            # positional block flag: get(block, ...) / put(item, block, ...)
            block_idx = 0 if attr == "get" else 1
            if len(node.args) > block_idx \
                    and isinstance(node.args[block_idx], ast.Constant) \
                    and node.args[block_idx].value is False:
                return None
            return "queue-block", name
        if attr == "wait" and kind in ("Event", "Condition"):
            return "sync-wait", name
        if attr == "join" and (
            kind == "Thread"
            or "thread" in parts[-2].lower()
            or "worker" in parts[-2].lower()
        ):
            return "thread-join", name
    return None


def _is_cv_wait_on_held(
    index: ProgramIndex,
    fn: FuncInfo,
    node: ast.Call,
    held: Tuple[LockDef, ...],
) -> bool:
    """True for ``cond.wait()`` while ``cond`` itself is among the held
    locks — the canonical condition-variable idiom (``with self._cond:
    while not pred: self._cond.wait()``).  ``wait`` RELEASES the lock it
    blocks on, so this is not blocking-under-lock; waiting on a condition
    while ALSO holding some other lock still is (only the condition's own
    lock is released for the duration of the wait)."""
    if not isinstance(node.func, ast.Attribute):
        return False
    lock = index.resolve_lock(fn, node.func.value)
    return lock is not None and all(h.id == lock.id for h in held) and bool(held)


def _is_logging_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if not name or "." not in name:
        return False
    parts = name.split(".")
    return parts[-1] in _LOG_METHODS and parts[-2] in ("logger", "logging", "log")


def _function_events(index: ProgramIndex, fn: FuncInfo) -> List[_Event]:
    events: List[_Event] = []

    def walk(node: ast.AST, held: Tuple[LockDef, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested defs run later, under whatever locks THEIR caller holds
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                walk(item.context_expr, held)
                lock = index.resolve_lock(fn, item.context_expr)
                if lock is not None:
                    ev = _Event("acquire", item.context_expr, inner)
                    ev.lock = lock
                    events.append(ev)
                    inner = inner + (lock,)
            for child in node.body:
                walk(child, inner)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.endswith(".acquire"):
                recv = node.func.value if isinstance(node.func, ast.Attribute) else None
                lock = index.resolve_lock(fn, recv) if recv is not None else None
                if lock is not None:
                    ev = _Event("acquire", node, held)
                    ev.lock = lock
                    events.append(ev)
            blocking = _blocking_category(index, fn, node)
            if blocking is not None:
                ev = _Event("blocking", node, held)
                ev.category, ev.label = blocking
                if blocking[0] == "sync-wait" and _is_cv_wait_on_held(
                    index, fn, node, held
                ):
                    ev.exempt = True
                events.append(ev)
            if _is_logging_call(node):
                ev = _Event("blocking", node, held)
                ev.category, ev.label = "logging", dotted_name(node.func) or "log"
                events.append(ev)
            callee = index.resolve_call(fn, node)
            if callee is not None and callee is not fn:
                ev = _Event("call", node, held)
                ev.callee = callee
                events.append(ev)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in getattr(fn.node, "body", []):
        walk(stmt, ())
    return events


class _Summary:
    """Transitive view of a function: locks it may acquire and blocking
    operations it may perform, each with a witness call chain."""

    __slots__ = ("acquires", "blocking")

    def __init__(self) -> None:
        #: lock id -> (LockDef, chain of "qname:line" hops)
        self.acquires: Dict[str, Tuple[LockDef, Tuple[str, ...]]] = {}
        #: (category, label, chain) — logging excluded (signal rule only)
        self.blocking: List[Tuple[str, str, Tuple[str, ...]]] = []


class ConcurrencyAnalyzer:
    def __init__(self, index: ProgramIndex) -> None:
        self.index = index
        self._events: Dict[int, List[_Event]] = {}
        self._summaries: Dict[int, _Summary] = {}
        self._in_progress: Set[int] = set()

    def events(self, fn: FuncInfo) -> List[_Event]:
        key = id(fn)
        if key not in self._events:
            self._events[key] = _function_events(self.index, fn)
        return self._events[key]

    def summary(self, fn: FuncInfo, depth: int = 0) -> _Summary:
        return self._summary_impl(fn, depth)[0]

    def _summary_impl(self, fn: FuncInfo, depth: int) -> Tuple[_Summary, bool]:
        """(summary, complete).  A summary truncated by the depth cap or
        the recursion guard is returned for THIS query but never cached:
        caching it would let the pruned view of a mutually recursive
        function shadow the full one on every later query (a sticky
        false negative).  Incomplete components are simply recomputed —
        the depth cap bounds the work."""
        key = id(fn)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached, True
        out = _Summary()
        if depth > _MAX_CALL_DEPTH or key in self._in_progress:
            return out, False  # recursion / depth cap: contribute nothing
        complete = True
        self._in_progress.add(key)
        try:
            for ev in self.events(fn):
                site = f"{fn.qname}:{getattr(ev.node, 'lineno', 0)}"
                if ev.kind == "acquire" and ev.lock is not None:
                    out.acquires.setdefault(ev.lock.id, (ev.lock, (site,)))
                elif ev.kind == "blocking" and ev.category != "logging":
                    out.blocking.append((ev.category, ev.label, (site,)))
                elif ev.kind == "call" and ev.callee is not None:
                    sub, sub_complete = self._summary_impl(ev.callee, depth + 1)
                    complete = complete and sub_complete
                    for lock_id, (lock, chain) in sub.acquires.items():
                        out.acquires.setdefault(lock_id, (lock, (site,) + chain))
                    for cat, label, chain in sub.blocking:
                        out.blocking.append((cat, label, (site,) + chain))
        finally:
            self._in_progress.discard(key)
        if complete:
            self._summaries[key] = out
        return out, complete

    def all_functions(self) -> List[FuncInfo]:
        out: List[FuncInfo] = []

        def add(fn: FuncInfo) -> None:
            out.append(fn)
            for child in fn.children.values():
                add(child)

        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                add(fn)
            for cls in mod.classes.values():
                for fn in cls.methods.values():
                    add(fn)
        return out


# ---------------------------------------------------------------------------
# the pass: graph edges, cycles, blocking, signal handlers
# ---------------------------------------------------------------------------


class _Reporter:
    """LintContext.report's suppression semantics, per source module."""

    def __init__(self, index: ProgramIndex) -> None:
        self.index = index
        self.diagnostics: List[Diagnostic] = []
        self._seen: Set[Tuple[str, str, int, str]] = set()

    def report(self, rule: Any, mod: ModuleInfo, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        sup = mod.suppressions.get(line)
        if sup is None and line in mod.suppressions:
            return
        if sup is not None and rule.id in sup:
            return
        key = (rule.id, mod.filename, line, message.split(";")[0])
        if key in self._seen:
            return
        self._seen.add(key)
        self.diagnostics.append(
            Diagnostic(
                rule=rule.id,
                severity=rule.severity,
                message=message,
                file=mod.filename,
                line=line + mod.line_offset,
                col=getattr(node, "col_offset", 0),
            )
        )


def _chain_str(chain: Sequence[str]) -> str:
    return " -> ".join(chain)


def _held_str(held: Sequence[LockDef]) -> str:
    return " -> ".join(lock.id for lock in held)


def run_concurrency_pass(
    index: ProgramIndex, rules: Sequence[Any]
) -> List[Diagnostic]:
    by_id = {r.id: r for r in rules}
    cycle_rule = by_id.get("lock-order-cycle")
    blocking_rule = by_id.get("blocking-under-lock")
    signal_rule = by_id.get("signal-handler-unsafe")
    analyzer = ConcurrencyAnalyzer(index)
    reporter = _Reporter(index)

    # -- lock graph + blocking-under-lock, one sweep over every function --
    # edge: (held lock id, acquired lock id) -> (witness mod, node, text)
    edges: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.AST, str]] = {}
    locks_by_id: Dict[str, LockDef] = {}

    for fn in analyzer.all_functions():
        for ev in analyzer.events(fn):
            if not ev.held:
                continue
            holder = ev.held[-1]  # innermost: outer edges exist transitively
            locks_by_id[holder.id] = holder
            site = f"{fn.qname}:{getattr(ev.node, 'lineno', 0)}"
            if ev.kind == "acquire" and ev.lock is not None:
                locks_by_id[ev.lock.id] = ev.lock
                if ev.lock.id != holder.id:
                    edges.setdefault(
                        (holder.id, ev.lock.id),
                        (fn.module, ev.node, site),
                    )
                elif ev.lock.kind == "Lock":
                    # non-reentrant self-acquire: guaranteed self-deadlock
                    if cycle_rule is not None:
                        reporter.report(
                            cycle_rule,
                            fn.module,
                            ev.node,
                            f"re-acquire of non-reentrant lock `{ev.lock.id}` "
                            f"already held at {site}: this thread deadlocks "
                            "itself (use RLock only if re-entry is intended)",
                        )
            elif ev.kind == "blocking" and ev.category != "logging" \
                    and not ev.exempt:
                if blocking_rule is not None:
                    reporter.report(
                        blocking_rule,
                        fn.module,
                        ev.node,
                        f"`{ev.label}` ({ev.category}) while holding "
                        f"{_held_str(ev.held)}: every thread contending on "
                        "that lock stalls for the call's duration; move the "
                        "blocking work outside the critical section",
                    )
            elif ev.kind == "call" and ev.callee is not None:
                sub = analyzer.summary(ev.callee, 1)
                for lock_id, (lock, chain) in sub.acquires.items():
                    locks_by_id[lock_id] = lock
                    if lock_id != holder.id:
                        edges.setdefault(
                            (holder.id, lock_id),
                            (fn.module, ev.node, _chain_str((site,) + chain)),
                        )
                    elif lock.kind == "Lock" and cycle_rule is not None:
                        # the callee re-takes a non-reentrant lock this
                        # frame already holds: guaranteed self-deadlock
                        # (self.* resolution means same instance)
                        reporter.report(
                            cycle_rule,
                            fn.module,
                            ev.node,
                            f"call chain re-acquires non-reentrant lock "
                            f"`{lock.id}` already held "
                            f"(via {_chain_str((site,) + chain)}): this "
                            "thread deadlocks itself — split an unlocked "
                            "`_foo_locked` helper or use RLock if re-entry "
                            "is intended",
                        )
                if blocking_rule is not None and sub.blocking:
                    # one diagnostic per call site: every blocking op the
                    # callee can reach is the same decision for the reader
                    # (and one line-level suppression either way)
                    cat, label, chain = sub.blocking[0]
                    extra = len(sub.blocking) - 1
                    more = f" (+{extra} more blocking op(s) on this path)" if extra else ""
                    reporter.report(
                        blocking_rule,
                        fn.module,
                        ev.node,
                        f"call chain reaches `{label}` ({cat}){more} while "
                        f"holding {_held_str(ev.held)} "
                        f"(via {_chain_str((site,) + chain)}); move the "
                        "blocking work outside the critical section",
                    )

    # -- cycles ------------------------------------------------------------
    if cycle_rule is not None:
        adj: Dict[str, List[str]] = {}
        for (u, v) in edges:
            adj.setdefault(u, []).append(v)

        def find_path(start: str, goal: str) -> Optional[List[str]]:
            stack = [(start, [start])]
            visited = {start}
            while stack:
                cur, path = stack.pop()
                for nxt in adj.get(cur, ()):
                    if nxt == goal:
                        return path + [goal]
                    if nxt not in visited:
                        visited.add(nxt)
                        stack.append((nxt, path + [nxt]))
            return None

        reported_cycles: Set[Tuple[str, ...]] = set()
        for (u, v), (mod, node, witness) in sorted(edges.items()):
            back = find_path(v, u)
            if back is None:
                continue
            canon = tuple(sorted({u, *back}))  # the cycle's node set
            if canon in reported_cycles:
                continue
            reported_cycles.add(canon)
            legs = []
            for a, b in zip([u] + back[:-1], back):
                leg = edges.get((a, b))
                legs.append(f"{a} -> {b}" + (f" at {leg[2]}" if leg else ""))
            reporter.report(
                cycle_rule,
                mod,
                node,
                "lock-order cycle (potential deadlock): "
                + "; ".join(legs)
                + " — pick one order and hold to it everywhere "
                "(docs/lint.md documents the intended hierarchy)",
            )

    # -- signal handlers ---------------------------------------------------
    if signal_rule is not None:
        for mod in index.modules.values():
            for node, handler_expr, fn_ctx in mod.signal_registrations:
                _check_signal_handler(
                    index, analyzer, reporter, signal_rule, mod, node,
                    handler_expr, fn_ctx,
                )

    return reporter.diagnostics


def _resolve_handler(
    index: ProgramIndex,
    mod: ModuleInfo,
    expr: ast.AST,
    fn_ctx: Optional[FuncInfo],
) -> Optional[FuncInfo]:
    name = dotted_name(expr)
    if name is None:
        return None
    if name.startswith("signal.SIG") or name.endswith(("SIG_IGN", "SIG_DFL")):
        return None
    parts = name.split(".")
    if parts[0] == "self" and len(parts) == 2 and fn_ctx is not None \
            and fn_ctx.cls is not None:
        return index.class_method(fn_ctx.cls, parts[1])
    if len(parts) == 1:
        scope = fn_ctx
        while scope is not None:
            if parts[0] in scope.children:
                return scope.children[parts[0]]
            scope = scope.parent
        return mod.functions.get(parts[0])
    return None


def _check_signal_handler(
    index: ProgramIndex,
    analyzer: ConcurrencyAnalyzer,
    reporter: _Reporter,
    rule: Any,
    mod: ModuleInfo,
    reg_node: ast.Call,
    handler_expr: ast.AST,
    fn_ctx: Optional[FuncInfo],
) -> None:
    problems: List[str] = []
    if isinstance(handler_expr, ast.Lambda):
        # a lambda body only has room for the safe patterns (sys.exit,
        # flag writes); scan its calls directly
        for sub in ast.walk(handler_expr.body):
            if isinstance(sub, ast.Call):
                name = dotted_name(sub.func) or ""
                if name in _HANDLER_SAFE_CALLS:
                    continue
                cat = _BLOCKING_CALLS.get(name)
                if cat or _is_logging_call(sub):
                    problems.append(f"calls `{name}`")
    else:
        handler = _resolve_handler(index, mod, handler_expr, fn_ctx)
        if handler is None:
            return  # unresolvable (prev-handler variable, C-level): no claim
        summary = analyzer.summary(handler)
        for lock_id, (lock, chain) in summary.acquires.items():
            problems.append(
                f"acquires `{lock_id}` (via {_chain_str(chain)})"
            )
        for cat, label, chain in summary.blocking:
            problems.append(f"reaches `{label}` ({cat}) via {_chain_str(chain)}")
        # logging: collected separately so blocking-under-lock stays quiet
        # about it, but a handler logging IS a deadlock (non-reentrant
        # logging module lock, possibly held by the interrupted frame)
        for ev in analyzer.events(handler):
            if ev.kind == "blocking" and ev.category == "logging":
                problems.append(
                    f"logs via `{ev.label}` at line {getattr(ev.node, 'lineno', 0)}"
                )
    if problems:
        reporter.report(
            rule,
            mod,
            reg_node,
            "signal handler goes beyond the flag-set pattern: "
            + "; ".join(sorted(set(problems))[:4])
            + " — handlers interrupt the main thread mid-bytecode, so any "
            "lock (including logging's) the interrupted frame holds "
            "deadlocks; set a plain flag and do the work on a normal thread",
        )


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def analyze_program_sources(
    sources: Dict[str, str],
    rules: Sequence[Any],
    *,
    line_offsets: Optional[Dict[str, int]] = None,
) -> List[Diagnostic]:
    """Run every whole-program pass (concurrency + SPMD) over
    {filename: source} — ONE shared index, each pass picking up the rule
    ids it implements."""
    if not rules:
        return []
    index = ProgramIndex()
    for filename, source in sources.items():
        index.add_source(
            filename, source, line_offset=(line_offsets or {}).get(filename, 0)
        )
    from determined_tpu.lint._spmd import run_spmd_pass

    return run_concurrency_pass(index, rules) + run_spmd_pass(index, rules)


def collect_py_files(path: str, exclude: Sequence[str] = ()) -> List[str]:
    """Every ``.py`` under ``path`` (or the file itself).

    ``exclude``: fnmatch globs tested against each candidate's basename
    AND its path relative to ``path`` — and against DIRECTORY names while
    walking, so an excluded tree (a live experiment's ``checkpoints/`` or
    ``traces/`` dir, a generated-code directory) is pruned without
    touching its contents rather than filtered file by file.  Linting a
    live checkout must not descend into journal/checkpoint artifacts:
    they can hold thousands of entries (and context dirs ship user
    ``.py`` files that are not this program).
    """
    import fnmatch

    def excluded(rel: str, name: str) -> bool:
        return any(
            fnmatch.fnmatch(name, pat) or fnmatch.fnmatch(rel, pat)
            for pat in exclude
        )

    if os.path.isfile(path):
        # an explicitly named file is ALWAYS linted: excludes exist to
        # prune artifacts discovered while WALKING a directory, not to
        # silently drop a target the user spelled out (analyze_path makes
        # the same promise for its file mode)
        return [path]
    out: List[str] = []
    for root, dirs, files in os.walk(path):
        rel_root = os.path.relpath(root, path)
        dirs[:] = sorted(
            d
            for d in dirs
            if d != "__pycache__"
            and not d.startswith(".")
            and not excluded(os.path.normpath(os.path.join(rel_root, d)), d)
        )
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            rel = os.path.normpath(os.path.join(rel_root, name))
            if excluded(rel, name):
                continue
            out.append(os.path.join(root, name))
    return out
