"""Control-plane contract analyzer (``dtpu lint --native``).

The master's API contract lives in three places that can silently drift:
the ``srv.route(...)`` dispatch table in ``native/master/master.cpp``, the
Python bindings (``client.py`` / ``api/spec.py`` / generated
``api/bindings.py``), and the fake masters that pin driver behavior in
tests.  The durability contract has the same shape: every ``record(...)``
WAL emit site needs a replay arm in ``apply_event``, snapshot coverage in
``snapshot_state``/``restore_snapshot``, and a torn-tail fuzz fixture.
PRs 13/15/16/18 audited all of this by eye; this module makes the audit
mechanical.

It is a **pattern-anchored structural parser**, not a C++ frontend: it
leans on the shapes the native sources already keep (and that
``scripts/native_check.sh`` now guards):

- routes:      ``srv.route("METHOD", "/path", wrapper([...]{...}))``
               with method + path on the route line; ``authed(`` /
               ``admin_only(`` / ``ingest_guarded(`` wrappers named on
               that same line;
- WAL emits:   ``record(Json::object().set("type", "x")...)`` or
               ``record(ev)`` where ``ev.set("type", "x")`` appears in
               the preceding lines of the same function;
- replay:      ``type == "x"`` arms inside ``apply_event``;
- snapshot:    member identifiers (trailing ``_``) referenced in
               ``snapshot_state`` / ``restore_snapshot``;
- metrics:     ``dtpu_*`` names in string literals of the ``/metrics``
               handler;
- wire bodies: ``body.set("k", ...)`` keys POSTed by the agent via
               ``master_req`` vs ``body["k"]`` / ``contains("k")`` reads
               in the matching master handler.

Everything lands in a :class:`NativeIndex`, which the ``native = True``
rules in ``rules/native.py`` cross-reference against the Python side
(route literals in the package, ``api/spec.py`` ROUTES, ``API.md`` rows,
``docs/operations.md`` metric names, the devcluster fuzz fixtures, and
the test suite's fake masters).  Findings flow through the same
``Diagnostic`` / suppression / JSON machinery as every other pass; the
C++ sources take ``// dtpu: lint-ok[rule] argument`` comments with the
same semantics as the Python form (a comment alone on its line also
covers the next line).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from determined_tpu.lint._ast import parse_suppressions
from determined_tpu.lint._diag import Diagnostic

__all__ = [
    "NativeIndex",
    "NativeSources",
    "Route",
    "WalSite",
    "build_native_index",
    "collect_native_sources",
    "find_native_root",
    "lint_native",
    "run_native_pass",
]


# --------------------------------------------------------------------------
# index data model
# --------------------------------------------------------------------------


@dataclass
class Route:
    """One ``srv.route`` dispatch entry."""

    method: str
    path: str            # as written ("/api/v1/trials/{id}/exit")
    norm: str            # placeholders collapsed ("/api/v1/trials/{}/exit")
    auth: str            # "authed", "admin_only", "ingest_guarded+authed", "anon"
    line: int
    status_codes: Tuple[int, ...] = ()


@dataclass
class WalSite:
    """One ``record(...)`` emit site, resolved to its record type."""

    rtype: Optional[str]  # None when the type literal could not be resolved
    line: int


@dataclass
class WireField:
    """One key of a JSON body the agent POSTs to the master."""

    key: str             # "slots" or "allocations[].trial_id"
    line: int


@dataclass
class WirePayload:
    """One agent->master request body (``master_req`` with a ``.dump()``)."""

    method: str
    norm: str
    line: int
    fields: List[WireField] = field(default_factory=list)


@dataclass
class FakeRoute:
    """One (method, path-pattern) a fake master's do_* handler answers."""

    method: str
    kind: str            # "exact" | "prefix" | "suffix" | "prefix+suffix" | "segments"
    data: Tuple          # kind-specific payload (see _match_fake_route)
    line: int
    cls: str


@dataclass
class NativeIndex:
    """Everything the analyzer extracted from the native control plane."""

    routes: List[Route] = field(default_factory=list)
    wal_sites: List[WalSite] = field(default_factory=list)
    replay_arms: Dict[str, int] = field(default_factory=dict)       # type -> line
    replay_members: Dict[str, Set[str]] = field(default_factory=dict)
    snapshot_text: str = ""       # snapshot_state + restore_snapshot bodies
    snapshot_line: int = 0
    dump_state_keys: List[str] = field(default_factory=list)
    metrics: List[Tuple[str, int]] = field(default_factory=list)    # (name, line)
    wire_payloads: List[WirePayload] = field(default_factory=list)
    wal_symbols: Set[str] = field(default_factory=set)              # wal.hpp API

    def record_types(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for site in self.wal_sites:
            if site.rtype is not None:
                out.setdefault(site.rtype, []).append(site.line)
        return out


@dataclass
class NativeSources:
    """The file set one native pass cross-references.

    Each entry is ``(display_path, source_text)`` so fixtures in tests can
    use tiny synthetic files while the real pass uses repo-relative paths.
    """

    master: Tuple[str, str]
    agent: Optional[Tuple[str, str]] = None
    wal: Optional[Tuple[str, str]] = None
    spec: Optional[Tuple[str, str]] = None       # api/spec.py
    api_md: Optional[Tuple[str, str]] = None     # API.md
    ops_md: Optional[Tuple[str, str]] = None     # docs/operations.md
    fuzz: Optional[Tuple[str, str]] = None       # scripts/devcluster.py
    python: Dict[str, str] = field(default_factory=dict)   # route-literal scan set
    fakes: Dict[str, str] = field(default_factory=dict)    # fake-master test files


# --------------------------------------------------------------------------
# C++ text utilities
# --------------------------------------------------------------------------

_SUPPRESS_CPP_RE = re.compile(r"//\s*dtpu:\s*lint-ok(?:\[([^\]]+)\])?")


def cpp_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """``// dtpu: lint-ok[rule] why`` -> {line: rule ids} (None = all).

    Same contract as the Python ``parse_suppressions``: a comment alone on
    its line also covers the next line.
    """
    out: Dict[int, Optional[Set[str]]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_CPP_RE.search(text)
        if not m:
            continue
        rules = (
            {r.strip() for r in m.group(1).split(",") if r.strip()}
            if m.group(1) is not None
            else None
        )
        targets = [i]
        if not text[: m.start()].strip():
            targets.append(i + 1)
        for t in targets:
            prev = out.get(t, set())
            out[t] = None if (prev is None or rules is None) else prev | rules
    return out


def _strip_comments(source: str) -> str:
    """Blank ``//`` and ``/* */`` comments, preserving newlines and string
    literals, so pattern anchors never match commentary."""
    out: List[str] = []
    i, n = 0, len(source)
    while i < n:
        c = source[i]
        if c == '"':
            j = i + 1
            while j < n and source[j] != '"':
                j += 2 if source[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(source[i:j])
            i = j
        elif c == "'":
            j = i + 1
            while j < n and source[j] != "'":
                j += 2 if source[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(source[i:j])
            i = j
        elif source.startswith("//", i):
            j = source.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif source.startswith("/*", i):
            j = source.find("*/", i)
            j = n if j < 0 else j + 2
            out.append("".join("\n" if ch == "\n" else " " for ch in source[i:j]))
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _line_of(source: str, idx: int) -> int:
    return source.count("\n", 0, idx) + 1


def _balanced_span(source: str, open_idx: int, open_ch: str = "(", close_ch: str = ")") -> int:
    """Index just past the close matching ``source[open_idx]`` (which must
    be ``open_ch``); string literals are skipped.  Returns ``len(source)``
    when unbalanced."""
    depth = 0
    i, n = open_idx, len(source)
    while i < n:
        c = source[i]
        if c == '"':
            i += 1
            while i < n and source[i] != '"':
                i += 2 if source[i] == "\\" else 1
        elif c == "'":
            i += 1
            while i < n and source[i] != "'":
                i += 2 if source[i] == "\\" else 1
        elif c == open_ch:
            depth += 1
        elif c == close_ch:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return n


_PLACEHOLDER_RE = re.compile(r"\{[^}]*\}")


def norm_path(path: str) -> str:
    """Collapse every ``{...}`` placeholder so spellings that differ only
    in parameter names compare equal (``{id}`` vs ``{trial_id}`` vs
    ``{*rest}``)."""
    return _PLACEHOLDER_RE.sub("{}", path)


# --------------------------------------------------------------------------
# master.cpp parsers
# --------------------------------------------------------------------------

_ROUTE_RE = re.compile(
    r"srv\s*\.\s*route\(\s*(?:\"(?P<method>[A-Z]+)\"|(?P<var>[A-Za-z_]\w*))\s*,\s*\"(?P<path>[^\"]+)\""
)
_METHOD_LIST_RE = re.compile(r"\{\s*\"[A-Z]+\"(?:\s*,\s*\"[A-Z]+\")*\s*\}")
_AUTH_WRAPPERS = ("ingest_guarded", "admin_only", "authed")


def _parse_routes(stripped: str) -> List[Route]:
    routes: List[Route] = []
    matches = list(_ROUTE_RE.finditer(stripped))
    for i, m in enumerate(matches):
        line = _line_of(stripped, m.start())
        span_end = matches[i + 1].start() if i + 1 < len(matches) else min(len(stripped), m.end() + 20000)
        handler = stripped[m.start():span_end]
        # auth wrapper(s): named on the route line itself
        route_line_end = stripped.find("\n", m.start())
        route_line = stripped[m.start(): route_line_end if route_line_end > 0 else len(stripped)]
        wrappers = [w for w in _AUTH_WRAPPERS if w + "(" in route_line]
        auth = "+".join(wrappers) if wrappers else "anon"
        codes = tuple(sorted({int(c) for c in re.findall(r"R::error\(\s*(\d{3})", handler)}))
        methods: List[str]
        if m.group("method"):
            methods = [m.group("method")]
        else:
            # e.g.  for (const char* method : {"GET", "POST", ...})
            #         srv.route(method, "/proxy/{id}/{*rest}", proxy_handler);
            back = stripped[max(0, m.start() - 400): m.start()]
            lst = None
            for lst in _METHOD_LIST_RE.finditer(back):
                pass
            methods = re.findall(r"\"([A-Z]+)\"", lst.group(0)) if lst else ["*"]
        path = m.group("path")
        for meth in methods:
            routes.append(Route(meth, path, norm_path(path), auth, line, codes))
    return routes


_RECORD_RE = re.compile(r"(?<![\w.])(?:m\s*\.\s*)?record\s*\(")
_SET_TYPE_RE = re.compile(r"\.set\(\s*\"type\"\s*,\s*\"([^\"]+)\"\s*\)")


def _parse_wal_sites(stripped: str) -> List[WalSite]:
    sites: List[WalSite] = []
    lines = stripped.splitlines()
    for m in _RECORD_RE.finditer(stripped):
        before = stripped[max(0, m.start() - 16): m.start()]
        if re.search(r"(?:void|auto)\s+$", before):
            continue  # the record() definition, not a call
        open_idx = stripped.index("(", m.start())
        end = _balanced_span(stripped, open_idx)
        arg = stripped[open_idx + 1: end - 1].strip()
        line = _line_of(stripped, m.start())
        tm = _SET_TYPE_RE.search(arg)
        rtype: Optional[str] = None
        if tm:
            rtype = tm.group(1)
        elif re.fullmatch(r"[A-Za-z_]\w*", arg):
            # record(ev): the builder set the type in the preceding lines
            pat = re.compile(r"(?<![\w])" + re.escape(arg) + r"\.set\(\s*\"type\"\s*,\s*\"([^\"]+)\"")
            for back in range(line - 2, max(-1, line - 82), -1):
                bm = pat.search(lines[back]) if back < len(lines) else None
                if bm:
                    rtype = bm.group(1)
                    break
        sites.append(WalSite(rtype, line))
    return sites


_TYPE_ARM_RE = re.compile(r"type\s*==\s*\"([a-z0-9_]+)\"")
_MEMBER_RE = re.compile(r"(?<![\w.>])([a-z][a-z0-9]*(?:_[a-z0-9]+)*_)(?![\w])")
_CALL_RE = re.compile(r"(?<![\w.>:])([a-z]\w+)\s*\(")

# members every arm may touch without a durability obligation: the journal
# machinery itself and the scheduler wakeup plumbing
_INFRA_MEMBERS = {"mu_", "work_cv_", "journal_", "journal_lines_", "events_"}


def _function_body(stripped: str, name_re: str) -> Tuple[str, int]:
    """Body text + first line of the first function whose signature matches
    ``name_re`` (a regex for ``<ret> <name>(``).  Empty when absent."""
    m = re.search(name_re, stripped)
    if not m:
        return "", 0
    brace = stripped.find("{", m.end())
    if brace < 0:
        return "", 0
    end = _balanced_span(stripped, brace, "{", "}")
    return stripped[brace:end], _line_of(stripped, m.start())


def _method_members(stripped: str, name: str, cache: Dict[str, Set[str]]) -> Set[str]:
    """Member identifiers referenced by the same-file method ``name``
    (one level: callees are not expanded further)."""
    if name in cache:
        return cache[name]
    cache[name] = set()  # cycle guard
    body, _ = _function_body(
        stripped, r"[\w:<>&*]+\s+" + re.escape(name) + r"\s*\([^;{]*\)\s*(?:const\s*)?\{"
    )
    cache[name] = set(_MEMBER_RE.findall(body)) if body else set()
    return cache[name]


def _parse_replay(stripped: str) -> Tuple[Dict[str, int], Dict[str, Set[str]]]:
    body, base_line = _function_body(stripped, r"void\s+apply_event\s*\(")
    if not body:
        return {}, {}
    arms: Dict[str, int] = {}
    members: Dict[str, Set[str]] = {}
    marks = list(_TYPE_ARM_RE.finditer(body))
    cache: Dict[str, Set[str]] = {}
    for i, m in enumerate(marks):
        rtype = m.group(1)
        arm = body[m.end(): marks[i + 1].start() if i + 1 < len(marks) else len(body)]
        arms.setdefault(rtype, base_line + body.count("\n", 0, m.start()))
        refs = set(_MEMBER_RE.findall(arm))
        for callee in set(_CALL_RE.findall(arm)):
            refs |= _method_members(stripped, callee, cache)
        members[rtype] = refs - _INFRA_MEMBERS
    return arms, members


def _parse_snapshot(stripped: str) -> Tuple[str, int]:
    snap, line = _function_body(stripped, r"Json\s+snapshot_state\s*\(")
    restore, _ = _function_body(stripped, r"void\s+restore_snapshot\s*\(")
    return snap + "\n" + restore, line


def _parse_dump_state(stripped: str) -> List[str]:
    body, _ = _function_body(stripped, r"Json\s+debug_state\s*\(")
    return sorted(set(re.findall(r"\.set\(\s*\"([\w.]+)\"", body)))


def _parse_metrics(stripped: str) -> List[Tuple[str, int]]:
    m = re.search(r"srv\s*\.\s*route\(\s*\"GET\"\s*,\s*\"/metrics\"", stripped)
    if not m:
        return []
    nxt = _ROUTE_RE.search(stripped, m.end())
    body = stripped[m.start(): nxt.start() if nxt else len(stripped)]
    base = m.start()
    seen: Dict[str, int] = {}
    for lit in re.finditer(r"\"([^\"\n]*)\"", body):
        for name in re.finditer(r"\bdtpu_\w+", lit.group(1)):
            seen.setdefault(name.group(0), _line_of(stripped, base + lit.start()))
    return sorted(seen.items(), key=lambda kv: (kv[1], kv[0]))


# --------------------------------------------------------------------------
# agent.cpp parser (wire payloads)
# --------------------------------------------------------------------------

_MASTER_REQ_RE = re.compile(r"master_req\(\s*\"(POST|PUT|PATCH)\"\s*,")


def _concat_to_norm(expr: str) -> Optional[str]:
    """``"/api/v1/trials/" + std::to_string(id) + "/exit"`` -> normalized
    path with ``{}`` for every non-literal piece."""
    pieces = re.findall(r"\"([^\"]*)\"", expr)
    if not pieces:
        return None
    if len(pieces) == 1 and expr.strip() == f'"{pieces[0]}"':
        return norm_path(pieces[0])
    return norm_path("{}".join([pieces[0]] + [p.lstrip() for p in pieces[1:]]))


def _split_args(text: str) -> List[str]:
    """Split a C++ argument list on top-level commas."""
    args: List[str] = []
    depth = 0
    cur: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            cur.append(text[i: j + 1])
            i = j + 1
            continue
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        elif c == "," and depth == 0:
            args.append("".join(cur).strip())
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    if cur:
        args.append("".join(cur).strip())
    return args


def _parse_wire_payloads(stripped: str) -> List[WirePayload]:
    payloads: List[WirePayload] = []
    lines = stripped.splitlines()
    for m in _MASTER_REQ_RE.finditer(stripped):
        open_idx = stripped.index("(", m.start())
        end = _balanced_span(stripped, open_idx)
        args = _split_args(stripped[open_idx + 1: end - 1])
        if len(args) < 3:
            continue
        path = _concat_to_norm(args[1])
        dm = re.fullmatch(r"([A-Za-z_]\w*)\s*\.\s*dump\(\)", args[2])
        if path is None or dm is None:
            continue
        var = dm.group(1)
        line = _line_of(stripped, m.start())
        # the payload is built in the lines just above the send: the
        # builder region starts at the LAST `var = Json::object()` before
        # the send, so an earlier same-named payload in the enclosing
        # function (or a neighboring one) never leaks its keys in
        lo = max(0, line - 60)
        builder_re = re.compile(r"(?<![\w])" + re.escape(var) + r"\s*=\s*Json::object\(\)")
        for back in range(line - 1, lo, -1):
            if back - 1 < len(lines) and builder_re.search(lines[back - 1]):
                lo = back - 1
                break
        region = "\n".join(lines[lo: line])
        fields: List[WireField] = []
        arrays: Dict[str, str] = {}  # array var -> top-level key
        for sm in re.finditer(
            r"(?<![\w])" + re.escape(var) + r"\.set\(\s*\"(\w+)\"\s*,\s*([A-Za-z_]\w*)?", region
        ):
            key, valvar = sm.group(1), sm.group(2)
            fields.append(WireField(key, lo + region.count("\n", 0, sm.start()) + 1))
            if valvar and re.search(
                r"(?<![\w])" + re.escape(valvar) + r"\s*=\s*Json::array\(\)", region
            ):
                arrays[valvar] = key
        for arr, key in arrays.items():
            for pm in re.finditer(r"(?<![\w])" + re.escape(arr) + r"\.push_back\(", region):
                pend = _balanced_span(region, region.index("(", pm.start()))
                elem = region[pm.start(): pend]
                for km in re.finditer(r"\.set\(\s*\"(\w+)\"", elem):
                    fields.append(
                        WireField(
                            f"{key}[].{km.group(1)}",
                            lo + region.count("\n", 0, pm.start() + km.start()) + 1,
                        )
                    )
                # elements built separately then pushed: el.set("k", ...)
                em = re.fullmatch(
                    r".*push_back\(\s*([A-Za-z_]\w*)\s*\)", elem, re.DOTALL
                )
                if em:
                    for km in re.finditer(
                        r"(?<![\w])" + re.escape(em.group(1)) + r"\.set\(\s*\"(\w+)\"", region
                    ):
                        fields.append(
                            WireField(
                                f"{key}[].{km.group(1)}",
                                lo + region.count("\n", 0, km.start()) + 1,
                            )
                        )
        payloads.append(WirePayload(m.group(1), path, line, fields))
    return payloads


# --------------------------------------------------------------------------
# python-side parsers
# --------------------------------------------------------------------------


def _parse_spec_routes(source: str) -> List[Tuple[str, str]]:
    """(method, normalized path) rows from api/spec.py's ROUTES list."""
    out: List[Tuple[str, str]] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "ROUTES" for t in node.targets
        )):
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            continue
        for row in node.value.elts:
            if isinstance(row, ast.Tuple) and len(row.elts) >= 2:
                meth, path = row.elts[0], row.elts[1]
                if isinstance(meth, ast.Constant) and isinstance(path, ast.Constant):
                    out.append((str(meth.value), norm_path(str(path.value))))
    return out


_API_ROW_RE = re.compile(r"^\|\s*`?([A-Z]+)`?\s*\|\s*`([^`]+)`", re.MULTILINE)


def _parse_api_md(text: str) -> Set[Tuple[str, str]]:
    return {(m.group(1), norm_path(m.group(2))) for m in _API_ROW_RE.finditer(text)}


_METRIC_TOKEN_RE = re.compile(r"dtpu_[\w{},]*[\w}]")


def _documented_metric_names(text: str) -> Set[str]:
    """Metric names mentioned in the operations doc, with ``{a,b}`` brace
    groups expanded — ``dtpu_reattach_{adopted,lost}_total`` documents
    both counters."""
    out: Set[str] = set()
    for tok in _METRIC_TOKEN_RE.findall(text):
        variants = [""]
        for piece in re.split(r"(\{[^{}]*\})", tok):
            if piece.startswith("{") and piece.endswith("}"):
                alts = piece[1:-1].split(",")
                variants = [v + a for v in variants for a in alts]
            else:
                variants = [v + piece for v in variants]
        out.update(variants)
    return out


_PY_ROUTE_LIT_RE = re.compile(r"[\"'](/(?:api|v1|proxy|metrics|debug)[^\"'\s]*)")


def _parse_python_route_literals(sources: Dict[str, str]) -> Set[str]:
    """Normalized route paths referenced anywhere in the Python package
    (plain strings and f-strings alike: ``{expr}`` already reads as a
    placeholder)."""
    out: Set[str] = set()
    for src in sources.values():
        for m in _PY_ROUTE_LIT_RE.finditer(src):
            out.add(norm_path(m.group(1).rstrip("?")))
    return out


def _parse_fuzz_types(source: str) -> Set[str]:
    """Record types covered by the devcluster ``sample_*_events``
    fixtures (the torn-tail fuzz corpus in test_master_wal)."""
    types: Set[str] = set()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return types
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef) and re.fullmatch(r"sample_\w*events", node.name)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for k, v in zip(sub.keys, sub.values):
                    if (
                        isinstance(k, ast.Constant) and k.value == "type"
                        and isinstance(v, ast.Constant) and isinstance(v.value, str)
                    ):
                        types.add(v.value)
    return types


# ---- fake masters ---------------------------------------------------------


def _path_expr(node: ast.AST) -> bool:
    """Is this expression the request path (``self.path`` or a local
    derived from it, conventionally named ``path``/``parts``)?"""
    if isinstance(node, ast.Attribute) and node.attr == "path":
        return True
    if isinstance(node, ast.Name) and node.id in ("path", "p"):
        return True
    if isinstance(node, ast.Subscript):
        return _path_expr(node.value)
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("split", "rstrip", "strip"):
            return _path_expr(f.value)
    return False


def _const_str(node: ast.AST) -> Optional[str]:
    return node.value if isinstance(node, ast.Constant) and isinstance(node.value, str) else None


def _collect_fake_conditions(test: ast.AST) -> List[Tuple[str, Tuple]]:
    """Route patterns asserted by one ``if`` test.  Returns a list of
    (kind, data) — ANDed terms merge (startswith+endswith, len+segments)."""
    terms: List[ast.AST] = (
        list(test.values) if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And) else [test]
    )
    exact: Optional[str] = None
    prefix: Optional[str] = None
    suffix: Optional[str] = None
    nseg: Optional[int] = None
    segs: Dict[int, str] = {}
    for t in terms:
        if isinstance(t, ast.Compare) and len(t.ops) == 1 and isinstance(t.ops[0], ast.Eq):
            left, right = t.left, t.comparators[0]
            # len(parts) == N
            if (
                isinstance(left, ast.Call)
                and isinstance(left.func, ast.Name)
                and left.func.id == "len"
                and isinstance(right, ast.Constant)
                and isinstance(right.value, int)
            ):
                nseg = right.value
                continue
            # parts[i] == "seg"
            if (
                isinstance(left, ast.Subscript)
                and isinstance(left.value, ast.Name)
                and left.value.id.startswith("part")
            ):
                idx = left.slice
                s = _const_str(right)
                if isinstance(idx, ast.Constant) and isinstance(idx.value, int) and s is not None:
                    segs[idx.value] = s
                continue
            # path == "..."
            s = _const_str(right)
            if s is not None and _path_expr(left):
                exact = s
        elif isinstance(t, ast.Call) and isinstance(t.func, ast.Attribute):
            s = _const_str(t.args[0]) if t.args else None
            if s is None or not _path_expr(t.func.value):
                continue
            if t.func.attr == "startswith":
                prefix = s
            elif t.func.attr == "endswith":
                suffix = s
    out: List[Tuple[str, Tuple]] = []
    if exact is not None:
        out.append(("exact", (exact,)))
    if prefix is not None and suffix is not None:
        out.append(("prefix+suffix", (prefix, suffix)))
    elif prefix is not None:
        out.append(("prefix", (prefix,)))
    elif suffix is not None:
        out.append(("suffix", (suffix,)))
    if nseg is not None and segs:
        out.append(("segments", (nseg, tuple(sorted(segs.items())))))
    return out


def _parse_fake_routes(source: str) -> List[FakeRoute]:
    """Route patterns each fake master's HTTP handlers answer.

    The handler class is usually an inner ``class Handler(...)`` built
    inside ``FakeMaster.__init__``, so qualification walks the lexical
    stack: any ``do_*`` method whose enclosing scopes include a name with
    both "Fake" and "Master" belongs to that fake.
    """
    routes: List[FakeRoute] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return routes

    def visit(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = stack + [child.name]
                joined = ".".join(sub)
                if (
                    isinstance(child, ast.FunctionDef)
                    and child.name.startswith("do_")
                    and "Fake" in joined
                    and "Master" in joined
                ):
                    owner = next(
                        (s for s in stack if "Fake" in s and "Master" in s), stack[-1] if stack else "?"
                    )
                    method = child.name[3:].upper()
                    for n in ast.walk(child):
                        if isinstance(n, ast.If):
                            for kind, data in _collect_fake_conditions(n.test):
                                routes.append(FakeRoute(method, kind, data, n.test.lineno, owner))
                visit(child, sub)
            else:
                visit(child, stack)

    visit(tree, [])
    return routes


def _seg_match(master_seg: str, seg: str) -> bool:
    return master_seg == "{}" or master_seg == seg


def _match_fake_route(fr: FakeRoute, master_routes: Sequence[Route]) -> bool:
    """Does any real master route answer what this fake pattern handles?"""
    candidates = [r for r in master_routes if r.method in (fr.method, "*")]
    if fr.kind == "exact":
        segs = [s for s in fr.data[0].strip("/").split("/") if s != ""]
        for r in candidates:
            rsegs = r.norm.strip("/").split("/")
            if len(rsegs) == len(segs) and all(_seg_match(a, b) for a, b in zip(rsegs, segs)):
                return True
        return False
    if fr.kind == "segments":
        nseg, pairs = fr.data
        for r in candidates:
            rsegs = r.norm.strip("/").split("/")
            if len(rsegs) == nseg and all(
                i < len(rsegs) and _seg_match(rsegs[i], s) for i, s in pairs
            ):
                return True
        return False

    def prefix_ok(rsegs: List[str], prefix: str) -> bool:
        whole = prefix.endswith("/")
        parts = [s for s in prefix.strip("/").split("/") if s != ""]
        if len(parts) > len(rsegs):
            return False
        for i, p in enumerate(parts):
            last = i == len(parts) - 1
            if last and not whole:
                if not (rsegs[i] == "{}" or rsegs[i].startswith(p)):
                    return False
            elif not _seg_match(rsegs[i], p):
                return False
        return True

    def suffix_ok(rsegs: List[str], suffix: str) -> bool:
        whole = suffix.startswith("/")
        parts = [s for s in suffix.strip("/").split("/") if s != ""]
        if len(parts) > len(rsegs):
            return False
        for j, p in enumerate(reversed(parts)):
            seg = rsegs[len(rsegs) - 1 - j]
            first = j == len(parts) - 1
            if first and not whole:
                if not (seg == "{}" or seg.endswith(p)):
                    return False
            elif not _seg_match(seg, p):
                return False
        return True

    for r in candidates:
        rsegs = r.norm.strip("/").split("/")
        if fr.kind == "prefix" and prefix_ok(rsegs, fr.data[0]):
            return True
        if fr.kind == "suffix" and suffix_ok(rsegs, fr.data[0]):
            return True
        if fr.kind == "prefix+suffix" and prefix_ok(rsegs, fr.data[0]) and suffix_ok(rsegs, fr.data[1]):
            return True
    return False


# --------------------------------------------------------------------------
# index construction
# --------------------------------------------------------------------------


def build_native_index(ns: NativeSources) -> NativeIndex:
    idx = NativeIndex()
    stripped = _strip_comments(ns.master[1])
    idx.routes = _parse_routes(stripped)
    idx.wal_sites = _parse_wal_sites(stripped)
    idx.replay_arms, idx.replay_members = _parse_replay(stripped)
    idx.snapshot_text, idx.snapshot_line = _parse_snapshot(stripped)
    idx.dump_state_keys = _parse_dump_state(stripped)
    idx.metrics = _parse_metrics(stripped)
    if ns.agent:
        idx.wire_payloads = _parse_wire_payloads(_strip_comments(ns.agent[1]))
    if ns.wal:
        wal_stripped = _strip_comments(ns.wal[1])
        idx.wal_symbols = set(
            re.findall(r"\b(?:bool|void|int64_t|size_t|std::string)\s+(\w+)\s*\(", wal_stripped)
        )
    return idx


# --------------------------------------------------------------------------
# the pass
# --------------------------------------------------------------------------


def run_native_pass(ns: NativeSources, rules: Sequence) -> List[Diagnostic]:
    """Cross-reference the :class:`NativeIndex` against the Python side and
    report through the ``native = True`` rules in ``rules``."""
    by_id = {r.id: r for r in rules if getattr(r, "native", False)}
    if not by_id:
        return []
    idx = build_native_index(ns)
    master_file = ns.master[0]
    raw: List[Diagnostic] = []

    def report(rule_id: str, file: str, line: int, message: str) -> None:
        rule = by_id.get(rule_id)
        if rule is not None:
            raw.append(Diagnostic(rule.id, rule.severity, message, file, line, 0))

    # ---- WAL contract ----------------------------------------------------
    rec_types = idx.record_types()
    fuzz_types = _parse_fuzz_types(ns.fuzz[1]) if ns.fuzz else None
    for rtype, sites in sorted(rec_types.items()):
        witness = f"{master_file}:{sites[0]}" + (
            f" (+{len(sites) - 1} more site{'s' if len(sites) > 2 else ''})" if len(sites) > 1 else ""
        )
        if rtype not in idx.replay_arms:
            report(
                "wal-replay-gap", master_file, sites[0],
                f"WAL record type '{rtype}' is emitted at {witness} but apply_event "
                f"has no `type == \"{rtype}\"` replay arm — the journaled mutation "
                "is lost at boot replay",
            )
        if fuzz_types is not None and rtype not in fuzz_types:
            report(
                "wal-fuzz-gap", master_file, sites[0],
                f"WAL record type '{rtype}' (emitted at {witness}) is missing from "
                f"the torn-tail fuzz fixtures in {ns.fuzz[0]} (sample_*_events) — "
                "truncation mid-record is never exercised for it",
            )
    for site in idx.wal_sites:
        if site.rtype is None:
            report(
                "wal-replay-gap", master_file, site.line,
                "record(...) call whose record type could not be resolved — keep the "
                '`.set("type", "...")` literal on the builder so replay coverage '
                "stays checkable",
            )
    if idx.snapshot_text:
        for rtype, arm_line in sorted(idx.replay_arms.items()):
            missing = sorted(
                m for m in idx.replay_members.get(rtype, set())
                if m not in idx.snapshot_text
            )
            if missing:
                report(
                    "wal-snapshot-gap", master_file, arm_line,
                    f"replay arm '{rtype}' touches member(s) {', '.join(missing)} that "
                    "snapshot_state/restore_snapshot never mention — the replayed state "
                    "is lost once the journal compacts into a snapshot",
                )

    # ---- route contract --------------------------------------------------
    spec_paths = {p for _, p in _parse_spec_routes(ns.spec[1])} if ns.spec else None
    api_rows = _parse_api_md(ns.api_md[1]) if ns.api_md else None
    py_lits = _parse_python_route_literals(ns.python) if ns.python else set()
    for r in idx.routes:
        if r.method == "*":
            continue
        if spec_paths is not None and r.norm not in spec_paths and r.norm not in py_lits:
            report(
                "route-unbound", master_file, r.line,
                f"master route {r.method} {r.path} ({r.auth}) has no api/spec.py entry "
                "and no route literal anywhere in the Python package — unreachable "
                "from the shipped client",
            )
        if api_rows is not None and (r.method, r.norm) not in api_rows:
            report(
                "route-undocumented", master_file, r.line,
                f"master route {r.method} {r.path} is missing from {ns.api_md[0]}'s "
                "live contract table (API.md is generated from api/spec.py: add a "
                "ROUTES row and regenerate)",
            )

    # ---- metrics contract ------------------------------------------------
    if ns.ops_md:
        documented = _documented_metric_names(ns.ops_md[1])
        for name, line in idx.metrics:
            if name not in documented:
                report(
                    "metric-undocumented", master_file, line,
                    f"/metrics emits '{name}' but {ns.ops_md[0]} never documents it",
                )

    # ---- fake-master conformance ----------------------------------------
    for fname, src in sorted(ns.fakes.items()):
        for fr in _parse_fake_routes(src):
            if not _match_fake_route(fr, idx.routes):
                shown = (
                    fr.data[0] if fr.kind in ("exact", "prefix", "suffix")
                    else " + ".join(str(d) for d in fr.data)
                )
                report(
                    "fake-master-conformance", fname, fr.line,
                    f"{fr.cls}.do_{fr.method} handles '{shown}' ({fr.kind}) but no "
                    f"real master route matches it — the fake pins driver behavior "
                    "the real control plane does not have",
                )

    # ---- wire payload symmetry ------------------------------------------
    if ns.agent:
        agent_file = ns.agent[0]
        routes_by_key = {(r.method, r.norm): r for r in idx.routes}
        stripped_master = _strip_comments(ns.master[1])
        route_matches = list(_ROUTE_RE.finditer(stripped_master))
        for wp in idx.wire_payloads:
            r = routes_by_key.get((wp.method, wp.norm))
            if r is None:
                continue  # route-unbound territory, not field symmetry
            # the handler span: from its route site to the next route
            start = end = None
            for i, mm in enumerate(route_matches):
                if _line_of(stripped_master, mm.start()) == r.line:
                    start = mm.start()
                    end = route_matches[i + 1].start() if i + 1 < len(route_matches) else len(stripped_master)
                    break
            if start is None:
                continue
            handler = stripped_master[start:end]
            if re.search(r"\brecord\(\s*body\s*\)", handler):
                continue  # body journaled wholesale; every key is "read"
            reads = set(re.findall(r"\[\s*\"(\w+)\"\s*\]", handler))
            reads |= set(re.findall(r"contains\(\s*\"(\w+)\"\s*\)", handler))
            for f in wp.fields:
                leaf = f.key.split(".")[-1].split("[")[0]
                if leaf not in reads:
                    report(
                        "wire-field-unread", agent_file, f.line,
                        f"agent payload for {wp.method} {wp.norm} sets '{f.key}' but "
                        f"the master handler ({master_file}:{r.line}) never reads it — "
                        "dead wire field: drop it or read it",
                    )

    # ---- suppressions ----------------------------------------------------
    supp: Dict[str, Dict[int, Optional[Set[str]]]] = {}

    def suppressed(d: Diagnostic) -> bool:
        if d.file not in supp:
            src = None
            if d.file == master_file:
                src = ns.master[1]
            elif ns.agent and d.file == ns.agent[0]:
                src = ns.agent[1]
            if src is not None:
                supp[d.file] = cpp_suppressions(src)
            elif d.file in ns.fakes:
                supp[d.file] = parse_suppressions(ns.fakes[d.file])
            else:
                supp[d.file] = {}
        rules_at = supp[d.file].get(d.line, set())
        return rules_at is None or d.rule in (rules_at or set())

    return sorted(
        (d for d in raw if not suppressed(d)),
        key=lambda d: (d.file, d.line, d.col, d.rule),
    )


# --------------------------------------------------------------------------
# repo wiring
# --------------------------------------------------------------------------

_MASTER_REL = os.path.join("native", "master", "master.cpp")


def find_native_root(start: str) -> Optional[str]:
    """Walk up from ``start`` to the directory that holds the native
    control plane (``native/master/master.cpp``)."""
    cur = os.path.abspath(start if os.path.isdir(start) else os.path.dirname(start) or ".")
    while True:
        if os.path.isfile(os.path.join(cur, _MASTER_REL)):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def _read_rel(root: str, rel: str) -> Optional[Tuple[str, str]]:
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return rel, f.read()


def collect_native_sources(root: str) -> NativeSources:
    """The real repo layout -> one :class:`NativeSources` set."""
    master = _read_rel(root, _MASTER_REL)
    if master is None:
        raise FileNotFoundError(f"no {_MASTER_REL} under {root}")
    python: Dict[str, str] = {}
    pkg = os.path.join(root, "determined_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                got = _read_rel(root, rel)
                if got:
                    python[got[0]] = got[1]
    fakes: Dict[str, str] = {}
    tests = os.path.join(root, "tests")
    if os.path.isdir(tests):
        for fn in sorted(os.listdir(tests)):
            if fn.startswith("test_") and fn.endswith(".py"):
                got = _read_rel(root, os.path.join("tests", fn))
                if got and "Master" in got[1] and "Fake" in got[1]:
                    fakes[got[0]] = got[1]
    return NativeSources(
        master=master,
        agent=_read_rel(root, os.path.join("native", "agent", "agent.cpp")),
        wal=_read_rel(root, os.path.join("native", "master", "wal.hpp")),
        spec=_read_rel(root, os.path.join("determined_tpu", "api", "spec.py")),
        api_md=_read_rel(root, "API.md"),
        ops_md=_read_rel(root, os.path.join("docs", "operations.md")),
        fuzz=_read_rel(root, os.path.join("scripts", "devcluster.py")),
        python=python,
        fakes=fakes,
    )


def lint_native(root: str, rules: Sequence) -> List[Diagnostic]:
    """Run the control-plane contract pass over the real repo at ``root``."""
    return run_native_pass(collect_native_sources(root), rules)
