"""Control-plane contract rules (``dtpu lint --native``).

These are ``program_level`` rules like the concurrency/SPMD sets, but they
run over the :class:`~determined_tpu.lint._native.NativeIndex` — the
pattern-anchored parse of the native master/agent sources — instead of the
Python ``ProgramIndex``.  The ``native = True`` marker is what
``lint/_native.py`` dispatches on; the Python program passes select rules
by id and ignore these.

Suppressions in C++ sources use the same comment form as Python:
``// dtpu: lint-ok[route-unbound] agent-internal; reached via master_req``.
"""

from __future__ import annotations

from determined_tpu.lint._diag import ERROR, WARNING
from determined_tpu.lint.rules import Rule, register


class NativeRule(Rule):
    """Base for rules driven by the native contract pass."""

    program_level = True
    #: dispatched by lint/_native.py, skipped by the Python program passes
    native = True


@register
class WalReplayGap(NativeRule):
    id = "wal-replay-gap"
    severity = ERROR
    description = (
        "a WAL record type is emitted by record(...) but apply_event has no "
        "replay arm for it — the acknowledged mutation vanishes at boot"
    )


@register
class WalSnapshotGap(NativeRule):
    id = "wal-snapshot-gap"
    severity = WARNING
    description = (
        "a replay arm mutates state that snapshot_state/restore_snapshot "
        "never serialize — replayed fine from the journal, lost after "
        "compaction folds the journal into a snapshot"
    )


@register
class WalFuzzGap(NativeRule):
    id = "wal-fuzz-gap"
    severity = WARNING
    description = (
        "an emitted WAL record type is absent from the devcluster "
        "sample_*_events fixtures, so the torn-tail fuzz in "
        "test_master_wal never truncates mid-record for it"
    )


@register
class RouteUnbound(NativeRule):
    id = "route-unbound"
    severity = WARNING
    description = (
        "a master route has no api/spec.py entry and no route literal "
        "anywhere in the Python package — dead dispatch or a missing binding"
    )


@register
class RouteUndocumented(NativeRule):
    id = "route-undocumented"
    severity = ERROR
    description = (
        "a master route is missing from API.md's live contract table "
        "(generated from api/spec.py and replayed against a live master by "
        "test_api_contract)"
    )


@register
class MetricUndocumented(NativeRule):
    id = "metric-undocumented"
    severity = WARNING
    description = (
        "/metrics emits a dtpu_* series that docs/operations.md never "
        "documents"
    )


@register
class FakeMasterConformance(NativeRule):
    id = "fake-master-conformance"
    severity = WARNING
    description = (
        "a test fake master answers a route the real master does not "
        "dispatch — the fake pins driver behavior the control plane lacks"
    )


@register
class WireFieldUnread(NativeRule):
    id = "wire-field-unread"
    severity = WARNING
    description = (
        "an agent->master payload field is emitted but the matching master "
        "handler never reads it — dead wire weight and a drifted contract"
    )
