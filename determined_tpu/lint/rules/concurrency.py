"""Whole-program concurrency rules: lock ordering, blocking under locks,
signal-handler safety.

The platform is now deeply threaded — scheduler trial threads, prefetch
workers, the tracer shipper, the journal, the GC thread, signal handlers —
and its lock-ordering invariants (``searcher -> journal``, the
``_ckpt_lock`` leaf rule, the scheduler queue handoffs) were enforced only
by code review; two hardening rounds each hand-caught a lock-order
inversion, a multi-GB ``rmtree`` under the searcher lock, and
fsync-under-lock stalls.  These three rules find that bug class
mechanically.  They are ``program_level``: ``lint/_concurrency.py`` builds
one cross-module index of every lock, ``with``-region, and resolvable call
in the lint target and drives the rules over it — a cycle between a lock
in ``experiment/journal.py`` and one in ``searcher/_searcher.py`` is only
visible to a pass that sees both files.

The runtime companion is ``lint/_runtime.py``'s ``LockOrderSentinel``,
which checks the ACTUAL acquisition DAG of a test process the same way the
retrace sentinel checks actual compiles.
"""

from __future__ import annotations

from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register


@register
class LockOrderCycleRule(Rule):
    id = "lock-order-cycle"
    severity = WARNING
    program_level = True
    description = (
        "cycle in the cross-module lock-acquisition graph: two code paths "
        "take the same locks in opposite orders — a potential deadlock the "
        "moment both paths run concurrently"
    )


@register
class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    severity = WARNING
    program_level = True
    description = (
        "blocking call (fsync, os.replace, shutil.rmtree, blocking "
        "queue.get/put, subprocess, network I/O, time.sleep, Thread.join, "
        "jax device sync) while a lock is held — every other thread "
        "touching that lock stalls for the call's full duration"
    )


@register
class SignalHandlerUnsafeRule(Rule):
    id = "signal-handler-unsafe"
    severity = WARNING
    program_level = True
    description = (
        "signal handler that acquires locks, logs, or does blocking I/O: "
        "handlers run on the main thread at ANY bytecode boundary, so a "
        "lock the interrupted frame already holds deadlocks the process — "
        "only the flag-set pattern (plain attribute writes, os.write) is "
        "reentrancy-safe"
    )
