"""Shape-/value-dependent Python control flow inside traced steps.

``if``/``while`` on a traced VALUE raises ``ConcretizationTypeError``
under jit; a traced value in a comparison that somehow concretizes (via a
host sync the author added to "fix" the error) makes the Python branch a
TRACE-TIME decision — the step recompiles whenever the branch flips, which
is exactly the retrace storm the runtime sentinel
(``lint/_runtime.py``) exists to catch.  ``for`` over a traced array
unrolls the loop into the program (compile-time blowup) when it works at
all.  Static metadata (``.shape``/``.dtype``/``len()``) is excluded: shape
math is host arithmetic and legal.
"""

from __future__ import annotations

import ast

from determined_tpu.lint._ast import references_traced_value
from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register


@register
class TracedControlFlowRule(Rule):
    id = "traced-control-flow"
    severity = WARNING
    step_scoped = True
    description = (
        "Python `if`/`while`/`for` on traced array VALUES: "
        "ConcretizationTypeError or a retrace per branch flip; use "
        "`jnp.where`/`lax.cond`/`lax.scan` (shape-based branching is fine)"
    )

    def _check(self, expr: ast.AST, node: ast.AST, ctx, kind: str) -> None:
        if not ctx.in_step:
            return
        if references_traced_value(expr, ctx.traced_names()):
            ctx.report(
                self,
                node,
                f"`{kind}` depends on a traced array value — under jit this "
                "is a ConcretizationTypeError or a retrace per distinct "
                "value; use `jnp.where`/`jax.lax.cond` (branch on `.shape`/"
                "`.dtype` instead if the decision is structural)",
            )

    def visit_if(self, node: ast.If, ctx) -> None:
        self._check(node.test, node, ctx, "if")

    def visit_while(self, node: ast.While, ctx) -> None:
        self._check(node.test, node, ctx, "while")

    def visit_for(self, node: ast.For, ctx) -> None:
        self._check(node.iter, node, ctx, "for ... in")
