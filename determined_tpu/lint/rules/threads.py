"""Lock-hygiene rule: unlocked mutation of state shared with threads.

The harness side got deeply concurrent (prefetch workers, per-trial
scheduler threads, background checkpoint writers) with no race detector —
the reference platform leans on Go's ``-race`` for exactly this class of
bug.  This rule is the static half of the answer (the runtime half is
``lint/_runtime.py``): find every ``threading.Thread(target=...)`` (and
``threading.Thread`` subclass ``run``), compute what state those thread
bodies touch, and flag mutations of that state — anywhere in the same
class, or inside the thread body itself for closure-captured names — that
are not under a ``with <lock>`` the analyzer can see.

Deliberately excluded as thread-safe by design: ``queue.Queue`` traffic
(``put``/``get``), ``threading.Event`` flips (``set``/``clear`` on
lockish-or-event names are method calls the rule does not treat as
container mutation), and ``__init__`` writes (they precede thread start).
A flagged site that is safe by a subtler argument (single-writer +
join-before-read, GIL-atomic dict store handed off through a queue) should
carry a ``# dtpu: lint-ok[unlocked-shared-state]`` suppression WITH a
justifying comment — the suppression is the audit trail.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from determined_tpu.lint._ast import dotted_name, local_names
from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register

_LOCKISH = ("lock", "mutex", "sem", "cond")
#: container mutations that are NOT internally synchronized
_MUTATORS = frozenset(
    {
        "append",
        "extend",
        "insert",
        "add",
        "update",
        "setdefault",
        "remove",
        "discard",
        "pop",
        "popitem",
        "clear",
    }
)


def _is_lockish(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if not name:
        return False
    last = name.split(".")[-1].lower()
    return any(t in last for t in _LOCKISH)


def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return bool(name) and name.split(".")[-1] == "Thread"


def _direct_functions(body: List[ast.stmt]) -> Dict[str, ast.AST]:
    return {
        s.name: s
        for s in body
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


#: constructor names whose instances are internally synchronized — method
#: calls on them (Event.clear, Queue.put, Lock.acquire) are not races
_SYNC_CTORS = frozenset(
    {
        "Event",
        "Queue",
        "LifoQueue",
        "PriorityQueue",
        "SimpleQueue",
        "Lock",
        "RLock",
        "Condition",
        "Semaphore",
        "BoundedSemaphore",
        "Barrier",
    }
)


def _sync_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes ``__init__`` binds to threading/queue sync primitives."""
    init = _direct_functions(cls.body).get("__init__")
    if init is None:
        return set()
    out: Set[str] = set()
    for sub in ast.walk(init):
        if not isinstance(sub, ast.Assign) or not isinstance(sub.value, ast.Call):
            continue
        ctor = dotted_name(sub.value.func)
        if ctor and ctor.split(".")[-1] in _SYNC_CTORS:
            for t in sub.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.add(t.attr)
    return out


def _self_attrs_referenced(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
        ):
            out.add(sub.attr)
    return out


def _self_method_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == "self"
        ):
            out.add(sub.func.attr)
    return out


def _local_fn_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
            out.add(sub.func.id)
    return out


class _Creation:
    def __init__(
        self,
        target_expr: ast.AST,
        class_node: Optional[ast.ClassDef],
        fn_stack: List[ast.AST],
    ) -> None:
        self.target_expr = target_expr
        self.class_node = class_node
        self.fn_stack = list(fn_stack)


class _Collector(ast.NodeVisitor):
    """Find Thread(target=...) creations + Thread-subclass run methods,
    remembering lexical scope for target resolution."""

    def __init__(self) -> None:
        self.creations: List[_Creation] = []
        self.thread_subclass_runs: List[Tuple[ast.ClassDef, ast.AST]] = []
        self._class: Optional[ast.ClassDef] = None
        self._fns: List[ast.AST] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node
        for base in node.bases:
            name = dotted_name(base)
            if name and name.split(".")[-1] == "Thread":
                run = _direct_functions(node.body).get("run")
                if run is not None:
                    self.thread_subclass_runs.append((node, run))
        self.generic_visit(node)
        self._class = prev

    def _visit_fn(self, node: ast.AST) -> None:
        self._fns.append(node)
        self.generic_visit(node)
        self._fns.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        if _is_thread_ctor(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    self.creations.append(
                        _Creation(kw.value, self._class, self._fns)
                    )
        self.generic_visit(node)


@register
class UnlockedSharedStateRule(Rule):
    id = "unlocked-shared-state"
    severity = WARNING
    description = (
        "write to state a `threading.Thread` target also touches, with no "
        "lock in sight: a data race unless a subtler handoff argument holds "
        "(if one does, suppress WITH the argument as a comment)"
    )

    # -- module pre-pass ---------------------------------------------------

    def before_module(self, tree: ast.AST, ctx) -> None:
        collector = _Collector()
        collector.visit(tree)

        class_targets: Dict[ast.ClassDef, List[ast.AST]] = {}
        local_targets: List[Tuple[ast.AST, List[ast.AST]]] = []

        for cls, run in collector.thread_subclass_runs:
            class_targets.setdefault(cls, []).append(run)

        for cr in collector.creations:
            expr = cr.target_expr
            # self.method target
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and cr.class_node is not None
            ):
                method = _direct_functions(cr.class_node.body).get(expr.attr)
                if method is not None:
                    class_targets.setdefault(cr.class_node, []).append(method)
                continue
            # local function target: resolve lexically outward
            if isinstance(expr, ast.Name):
                for scope in reversed(cr.fn_stack):
                    fn = _direct_functions(scope.body).get(expr.id)
                    if fn is not None:
                        local_targets.append((fn, cr.fn_stack))
                        if cr.class_node is not None:
                            # a closure target may still touch self.*
                            class_targets.setdefault(
                                cr.class_node, []
                            ).append(fn)
                        break

        for cls, targets in class_targets.items():
            self._check_class(cls, targets, ctx)
        for fn, stack in local_targets:
            self._check_local_target(fn, stack, ctx)

    # -- class-attribute sharing ------------------------------------------

    def _expand_targets(
        self, cls: ast.ClassDef, targets: List[ast.AST]
    ) -> List[ast.AST]:
        """Targets plus the class methods they (transitively) call — a
        worker that does its writes through ``self._put`` still shares
        ``self._queue``."""
        methods = _direct_functions(cls.body)
        seen: List[ast.AST] = []
        work = list(targets)
        while work:
            fn = work.pop()
            if fn in seen:
                continue
            seen.append(fn)
            for called in _self_method_calls(fn):
                m = methods.get(called)
                if m is not None and m not in seen:
                    work.append(m)
        return seen

    def _check_class(
        self, cls: ast.ClassDef, targets: List[ast.AST], ctx
    ) -> None:
        target_set = self._expand_targets(cls, targets)
        shared_attrs: Set[str] = set()
        for fn in target_set:
            shared_attrs |= _self_attrs_referenced(fn)
        if not shared_attrs:
            return
        sync = _sync_attrs(cls)
        target_names = sorted({getattr(t, "name", "?") for t in targets})
        for fn in _direct_functions(cls.body).values():
            if getattr(fn, "name", "") in ("__init__", "__post_init__", "__del__"):
                continue  # runs before threads start / after they matter
            self._scan_writes(
                fn,
                ctx,
                self_attrs=shared_attrs,
                closure_names=None,
                sync_attrs=sync,
                because=(
                    f"also touched by thread target(s) "
                    f"{', '.join(target_names)} of {cls.name}"
                ),
            )

    # -- closure sharing ---------------------------------------------------

    def _check_local_target(
        self, fn: ast.AST, stack: List[ast.AST], ctx
    ) -> None:
        """Mutations of closure-captured names inside a thread body: the
        enclosing function (the other thread) shares every free name."""
        bodies = [fn]
        # expand through sibling local functions the target calls
        # (sender -> flush in the log shipper)
        i = 0
        while i < len(bodies):
            for called in _local_fn_calls(bodies[i]):
                for scope in reversed(stack):
                    peer = _direct_functions(scope.body).get(called)
                    if peer is not None and peer not in bodies:
                        bodies.append(peer)
                        break
            i += 1
        for body in bodies:
            free = set()
            local = local_names(body)
            for sub in ast.walk(body):
                if isinstance(sub, ast.Name) and sub.id not in local:
                    free.add(sub.id)
            if free:
                self._scan_writes(
                    body,
                    ctx,
                    self_attrs=None,
                    closure_names=free,
                    sync_attrs=set(),
                    because=(
                        f"closure shared between thread target "
                        f"`{getattr(fn, 'name', '?')}` and its enclosing scope"
                    ),
                )

    # -- write scanning ----------------------------------------------------

    def _scan_writes(
        self,
        fn: ast.AST,
        ctx,
        *,
        self_attrs: Optional[Set[str]],
        closure_names: Optional[Set[str]],
        sync_attrs: Set[str],
        because: str,
    ) -> None:
        reported: Set[int] = set()

        def matches(expr: ast.AST, mutator_call: bool = False) -> Optional[str]:
            base = expr
            while isinstance(base, ast.Subscript):
                base = base.value
            name = dotted_name(base)
            if name is None:
                return None
            if name.startswith("self.") and name.split(".")[1] in sync_attrs:
                # Event/Queue/Lock attribute: its methods synchronize
                # internally; only REBINDING it is a write worth flagging
                if mutator_call:
                    return None
            if (
                self_attrs is not None
                and name.startswith("self.")
                and name.split(".")[1] in self_attrs
            ):
                return name
            if closure_names is not None and "." not in name and name in closure_names:
                # a free name being written: a plain-Store name would be a
                # local (and thus not free), so anything matching here is a
                # container mutation, subscript store, or a declared
                # nonlocal/global rebind — all shared writes
                return name
            return None

        def report(node: ast.AST, what: str) -> None:
            if id(node) in reported:
                return
            reported.add(id(node))
            ctx.report(
                self,
                node,
                f"unlocked write to `{what}` ({because}); hold a lock, hand "
                "off through a queue.Queue, or suppress with the safety "
                "argument as a comment",
            )

        def walk(node: ast.AST, protected: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                now = protected or any(
                    _is_lockish(item.context_expr) for item in node.items
                )
                for item in node.items:
                    walk(item.context_expr, protected)
                for child in node.body:
                    walk(child, now)
                return
            if not protected:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        hit = matches(t)
                        if hit:
                            report(node, hit)
                elif isinstance(node, ast.AugAssign):
                    hit = matches(node.target)
                    if hit:
                        report(node, hit)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        hit = matches(t)
                        if hit:
                            report(node, hit)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                        hit = matches(f.value, mutator_call=True)
                        if hit:
                            report(node, f"{hit}.{f.attr}(...)")
            for child in ast.iter_child_nodes(node):
                walk(child, protected)

        for stmt in getattr(fn, "body", []):
            walk(stmt, False)
