"""Host-device synchronization rules.

Inside a traced step every one of these forces a device->host round trip
(or fails outright under jit): the device pipeline drains, the overlapped
input feed stalls, and the "no host syncs in the hot loop" contract the
Trainer is built around (``train/_trainer.py``) is silently broken.
"""

from __future__ import annotations

import ast

from determined_tpu.lint._ast import call_name, references_traced_value
from determined_tpu.lint._diag import ERROR, WARNING
from determined_tpu.lint.rules import Rule, register

#: dotted call names that materialize a traced value on the host
_HOST_CALLS = {
    "np.asarray": "np.asarray",
    "numpy.asarray": "numpy.asarray",
    "np.array": "np.array",
    "numpy.array": "numpy.array",
    "jax.device_get": "jax.device_get",
}

#: builtins that concretize a traced array to a python scalar
_SCALAR_BUILTINS = {"float", "int", "bool"}


@register
class HostSyncRule(Rule):
    id = "host-sync"
    severity = ERROR
    step_scoped = True
    description = (
        "host-device sync inside a traced step: `.item()`, `float()`/`int()` "
        "on arrays, `np.asarray`/`jax.device_get` — blocks the device "
        "pipeline or raises ConcretizationTypeError under jit"
    )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        # `.item()` anywhere in a chain (x.item(), x.mean().item(), ...)
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            ctx.report(
                self,
                node,
                "`.item()` concretizes a traced value on the host; return it "
                "as a metric instead (the Trainer fetches metrics once per "
                "REPORT boundary)",
            )
            return
        name = call_name(node)
        if name is None:
            return
        if name in _HOST_CALLS:
            ctx.report(
                self,
                node,
                f"`{name}` pulls a traced value to the host; use `jnp.asarray`"
                " / keep the computation on device",
            )
            return
        if name in _SCALAR_BUILTINS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                return
            if references_traced_value(arg, ctx.traced_names()):
                ctx.report(
                    self,
                    node,
                    f"`{name}()` on a traced value is a host sync (or a "
                    "ConcretizationTypeError); use `.astype`/`jnp` casts to "
                    "stay on device",
                )


@register
class BlockUntilReadyRule(Rule):
    id = "block-until-ready"
    severity = ERROR
    step_scoped = True
    description = (
        "`.block_until_ready()` inside a traced step: stalls dispatch; it "
        "belongs in benchmarks, never in step code"
    )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        name = call_name(node)
        if name and name.endswith(".block_until_ready"):
            ctx.report(
                self,
                node,
                "`.block_until_ready()` blocks the host on device completion "
                "inside the step; drop it (the Trainer syncs once per REPORT "
                "boundary)",
            )


@register
class TracedPrintRule(Rule):
    id = "traced-print"
    severity = WARNING
    step_scoped = True
    description = (
        "`print` of traced values inside a step: prints a tracer (useless) "
        "or forces a sync; use `jax.debug.print`"
    )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        name = call_name(node)
        if name != "print":
            return
        # only prints OF TRACED VALUES: a static banner print is harmless
        # (it runs once at trace time, which is also what it looks like)
        traced = ctx.traced_names()
        args = list(node.args) + [kw.value for kw in node.keywords]
        if any(references_traced_value(a, traced) for a in args):
            ctx.report(
                self,
                node,
                "`print` under trace runs once at trace time and shows "
                "tracers, not values; use `jax.debug.print(...)` for "
                "runtime values",
            )
