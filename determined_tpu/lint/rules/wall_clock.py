"""Wall-clock reads inside traced step code.

``time.time()`` / ``datetime.now()`` under trace evaluate once at compile
time; the "timestamp" every step then reports is the tracing instant,
frozen into the executable — and shared across trials when the jit-reuse
cache hands the compiled step to the next trial.  Timing belongs at the
Trainer's boundaries (it already measures per-report wall time).
"""

from __future__ import annotations

import ast

from determined_tpu.lint._ast import call_name
from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register

_CLOCK_CALLS = frozenset(
    {
        "time.time",
        "time.perf_counter",
        "time.monotonic",
        "time.time_ns",
        "datetime.now",
        "datetime.utcnow",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
    }
)


@register
class WallClockRule(Rule):
    id = "wall-clock"
    severity = WARNING
    step_scoped = True
    description = (
        "`time.time()` / `datetime.now()` in a traced step: evaluates once "
        "at trace time, so the value is the compile instant, not the step "
        "time"
    )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        name = call_name(node)
        if name in _CLOCK_CALLS:
            ctx.report(
                self,
                node,
                f"`{name}()` freezes the trace-time clock into the compiled "
                "step; measure time at boundaries (callbacks / the Trainer's "
                "report metrics) instead",
            )
