"""Whole-program SPMD correctness rules: rank-divergence hazards.

Multi-host gangs (experiment/cluster.py + the devcluster harness) turned
the dominant harness failure mode from "a thread deadlocks" into "a rank
diverges": one process takes a different code path, issues a different
(or no) collective, and every healthy rank blocks into the 600-second
collective timeout with no diagnostics at all.  Both live instances of
this class (the ``_drain_pending_save`` healthy-ranks-hang, the gloo
checkpoint-thread/psum SIGABRT) were found by humans reading stack
dumps.  These five rules find the *code shapes* that produce it; they
are ``program_level`` and run over the same cross-module
``ProgramIndex`` the concurrency rules use (``lint/_spmd.py`` drives
them).

The runtime companion is ``lint/_runtime.py``'s
``CollectiveSequenceSentinel``, which digests the ACTUAL per-rank
collective sequence and converts a live divergence into a deterministic
``CollectiveDivergenceError`` instead of a hang.
"""

from __future__ import annotations

from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register


@register
class RankDependentCollectiveRule(Rule):
    id = "rank-dependent-collective"
    severity = WARNING
    program_level = True
    description = (
        "control flow conditioned on the process rank (jax.process_index(), "
        "dist.rank/is_chief, DTPU_RANK env) guards a collective on only "
        "some paths — ranks on the other path never enter it and the gang "
        "hangs to the collective timeout"
    )


@register
class ConditionalCollectiveEscapeRule(Rule):
    id = "conditional-collective-escape"
    severity = WARNING
    program_level = True
    description = (
        "a guarded raise/return/break between paired collectives, or a "
        "collective inside a loop with a rank-dependent trip count — the "
        "path where one rank exits the collective sequence early while its "
        "peers block; exchange the local fact first and escape on the "
        "exchanged (rank-uniform) value"
    )


@register
class UnorderedIterationFeedingCollectiveRule(Rule):
    id = "unordered-iteration-feeding-collective"
    severity = WARNING
    program_level = True
    description = (
        "iteration over a set / os.listdir / glob / iterdir issues "
        "collectives per element or builds a payload a collective carries "
        "— element order is not guaranteed to match across ranks, so the "
        "per-rank collective sequences (or payloads) disagree; iterate "
        "sorted(...)"
    )


@register
class RankGuardedIoMissingBarrierRule(Rule):
    id = "rank-guarded-io-missing-barrier"
    severity = WARNING
    program_level = True
    description = (
        "a chief-only (rank-guarded) filesystem write followed by an "
        "unguarded read with no collective between them — non-chief ranks "
        "race the chief's write and read a missing or half-written file"
    )


@register
class WallClockDivergenceRule(Rule):
    id = "wall-clock-divergence"
    severity = WARNING
    program_level = True
    description = (
        "wall-clock time or unseeded randomness decides whether a "
        "collective runs, or rides an operand that must be comparable "
        "across ranks — clocks and unseeded RNG differ on every host every "
        "run; decide from rank-uniform state or broadcast the chief's "
        "sample"
    )
