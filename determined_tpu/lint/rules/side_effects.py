"""Side effects under trace.

A traced step runs its Python body ONCE; mutations of ``self``, globals,
or closure containers happen at trace time only — they do not re-execute
per step, and when the jit-reuse cache shares a compiled step across
trials the mutation already happened against the FIRST trial's objects.
Worse, a mutated ``self`` read by the scheduler/prefetch threads is a race
the lock-hygiene rule can't even see.  State belongs in the TrainState or
in metrics; host-side bookkeeping belongs in callbacks.
"""

from __future__ import annotations

import ast

from determined_tpu.lint._ast import dotted_name, local_names
from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register

#: container mutators that leak trace-time writes into host objects
_MUTATORS = frozenset(
    {"append", "extend", "insert", "add", "update", "setdefault", "remove", "discard"}
)


@register
class TraceSideEffectRule(Rule):
    id = "trace-side-effect"
    severity = WARNING
    step_scoped = True
    description = (
        "mutating `self.*`/globals/closure containers inside a traced step: "
        "runs once at trace time, not per step (and races scheduler/prefetch "
        "threads)"
    )

    def visit_assign(self, node: ast.Assign, ctx) -> None:
        if not ctx.in_step:
            return
        for target in node.targets:
            self._check_target(target, node, ctx)

    def visit_augassign(self, node: ast.AugAssign, ctx) -> None:
        if not ctx.in_step:
            return
        self._check_target(node.target, node, ctx)

    def _check_target(self, target: ast.AST, node: ast.AST, ctx) -> None:
        # self.x = ... / self.x[...] = ... / self.x += ...
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        name = dotted_name(base)
        if name and (name == "self" or name.startswith("self.")):
            ctx.report(
                self,
                node,
                f"write to `{name}` inside a traced step happens once at "
                "trace time; carry state through the TrainState / return it "
                "as a metric",
            )

    def visit_global(self, node: ast.Global, ctx) -> None:
        if not ctx.in_step:
            return
        ctx.report(
            self,
            node,
            f"`global {', '.join(node.names)}` in a traced step: the write "
            "happens at trace time only",
        )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS):
            return
        name = dotted_name(fn.value)
        if name is None:
            return
        root = name.split(".")[0]
        if root == "self":
            ctx.report(
                self,
                node,
                f"`{name}.{fn.attr}(...)` mutates trial state under trace "
                "(runs once, at trace time)",
            )
            return
        # mutation of a name NOT local to any enclosing step function =
        # closure/global container captured by the trace.  Statement
        # position only: `x.update(...)` whose RESULT is consumed is the
        # functional idiom (optax), not a side effect.
        if id(node) not in ctx.stmt_calls:
            return
        step_fns = [f.node for f in ctx.func_stack if f.is_step]
        if not step_fns:
            return
        local_anywhere = any(root in local_names(fn) for fn in step_fns)
        if not local_anywhere:
            ctx.report(
                self,
                node,
                f"`{name}.{fn.attr}(...)` mutates a closure/global container "
                "under trace; collect values as step outputs instead",
            )
