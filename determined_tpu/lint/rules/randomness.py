"""Python-level randomness inside traced step code.

``random.*`` / ``np.random.*`` draw ONCE at trace time and bake the value
into the compiled program as a constant: every step then reuses the same
"random" number, and two trials sharing a compiled step through the
jit-reuse cache (``train/_jit_cache.py``) silently share the draw too.
``jax.random`` with keys threaded through the step is the correct form —
the Trainer already folds the step counter into the state rng.
"""

from __future__ import annotations

import ast

from determined_tpu.lint._ast import call_name
from determined_tpu.lint._diag import ERROR
from determined_tpu.lint.rules import Rule, register

_PY_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")


@register
class PythonRngRule(Rule):
    id = "python-rng"
    severity = ERROR
    step_scoped = True
    description = (
        "`random.*` / `np.random.*` in a traced step: draws once at trace "
        "time and freezes into the compiled program; use `jax.random` with "
        "a threaded key"
    )

    def visit_call(self, node: ast.Call, ctx) -> None:
        if not ctx.in_step:
            return
        name = call_name(node)
        if name is None:
            return
        if any(name.startswith(p) for p in _PY_RNG_PREFIXES):
            ctx.report(
                self,
                node,
                f"`{name}` is host randomness frozen at trace time; use "
                "`jax.random.<dist>(rng, ...)` with the step's rng key "
                "(the `rng` argument of `loss`)",
            )
