"""Mutable default arguments in trial code.

The classic Python footgun bites harder here: a trial class is
instantiated once PER TRIAL by the scheduler, concurrently — a mutable
default (``hparams={}``, ``metrics=[]``) is one shared object across every
trial in the search, so trial B reads hyperparameters trial A wrote.
Scoped to trial classes (module-wide it would re-litigate style choices
this analyzer has no business in).
"""

from __future__ import annotations

import ast

from determined_tpu.lint._diag import WARNING
from determined_tpu.lint.rules import Rule, register


@register
class MutableDefaultRule(Rule):
    id = "mutable-default"
    severity = WARNING
    description = (
        "mutable default argument in a trial class: one shared object "
        "across every (concurrent) trial instance"
    )

    def visit_functiondef(self, node: ast.AST, ctx) -> None:
        if not ctx.in_trial_class:
            return
        args = getattr(node, "args", None)
        if args is None:
            return
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("dict", "list", "set")
            ):
                ctx.report(
                    self,
                    default,
                    f"mutable default in `{getattr(node, 'name', '<fn>')}`: "
                    "evaluated once and shared by every trial instance the "
                    "scheduler creates; default to None and build inside",
                )
