"""Rule registry for the trial preflight analyzer.

Each rule is a class with a stable ``id`` (the name users put in
``# dtpu: lint-ok[<id>]`` suppressions and ``lint.suppress`` config), a
default ``severity``, and visitor hooks the AST walker dispatches to:

- ``before_module(tree, ctx)`` — whole-module pre-pass (the concurrency
  rule does its own cross-function analysis here);
- ``visit_call / visit_assign / visit_augassign / visit_if / visit_while /
  visit_for / visit_functiondef / visit_global (node, ctx)`` — per-node
  hooks, called during the single walk with full scope context.

Rules report through ``ctx.report(rule, node, message)``; suppression and
severity handling live in the context, so rules only decide *what* is a
finding.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from determined_tpu.lint._diag import ERROR, WARNING


class Rule:
    """Base class: subclass, set ``id``/``severity``/``description``, and
    implement whichever hooks the rule needs."""

    id: str = ""
    severity: str = WARNING
    description: str = ""
    #: True when the rule only fires inside traced step code (the walker
    #: still calls the hooks; the rule checks ``ctx.in_step`` itself — this
    #: flag is documentation + docs-table input)
    step_scoped: bool = False
    #: True for whole-program rules: they get no per-node walker hooks —
    #: ``lint/_concurrency.py`` drives them over an index of EVERY module
    #: in the lint target at once (lock graphs need cross-module edges).
    #: Registry/suppression/config handling is identical to walker rules.
    program_level: bool = False

    def before_module(self, tree, ctx) -> None:  # pragma: no cover - hook
        pass


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    return dict(_REGISTRY)


def build_rules(
    only: Optional[Sequence[str]] = None,
    disabled: Optional[Sequence[str]] = None,
) -> List[Rule]:
    """Instantiate the selected rule set (unknown ids raise: a typo'd
    suppression list silently linting everything would be worse)."""
    known = set(_REGISTRY)
    for name in list(only or []) + list(disabled or []):
        if name not in known:
            raise ValueError(f"unknown lint rule {name!r}; known: {sorted(known)}")
    ids = set(only) if only else known
    ids -= set(disabled or [])
    return [_REGISTRY[i]() for i in sorted(ids)]


# importing the rule modules populates the registry
from determined_tpu.lint.rules import (  # noqa: E402,F401
    concurrency,
    control_flow,
    defaults,
    host_sync,
    native,
    randomness,
    side_effects,
    spmd,
    threads,
    wall_clock,
)

__all__ = ["ERROR", "WARNING", "Rule", "all_rules", "build_rules", "register"]
