"""Fused blocked cross-entropy: lm_head matmul + softmax-CE without ever
materializing the full ``[tokens, vocab]`` logits in HBM.

Motivation (TPU): with V=32k vocab and f32 logits, the standard
``logits = x @ W; softmax_xent(logits)`` pattern writes B*S*V*4 bytes to HBM
and reads them back in the backward pass — ~1 GiB per step at d2048/s1024/b8
— which is pure bandwidth waste on a bandwidth-bound chip (BASELINE.md: the
bs16 step *regresses* because of it).  Here the token dimension is scanned
in chunks: each chunk computes its logits tile in bf16 on the MXU, reduces
to per-token loss in f32, and the tile dies in VMEM/registers.
``jax.checkpoint`` on the chunk body makes the backward pass recompute the
tile instead of storing it, so the only HBM traffic is x, W, and the scan
carry.  The extra recompute is one lm_head matmul (<5% of model FLOPs); the
saved traffic is the whole logits tensor, twice.

The reference has no analog (loss math lives in user pytorch code); this is
TPU-native design per SURVEY §7 hard-part (e).

Sharding: hidden is batch-sharded (dp/fsdp), the kernel may be
vocab-sharded (tp).  Everything here is plain jnp under jit, so XLA inserts
the psum for the vocab-sharded logsumexp per chunk.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _chunk_loss(
    x_chunk: jax.Array,       # [chunk, d]
    kernel: jax.Array,        # [d, vocab]
    tgt_chunk: jax.Array,     # [chunk] int; < 0 = ignore
    compute_dtype,
) -> Tuple[jax.Array, jax.Array]:
    """Sum of token losses + valid-token count for one chunk."""
    logits = jnp.dot(
        x_chunk.astype(compute_dtype),
        kernel.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )  # [chunk, vocab] f32 accumulate on the MXU, lives only inside the chunk
    valid = tgt_chunk >= 0
    safe_tgt = jnp.where(valid, tgt_chunk, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)                     # [chunk]
    tgt_logit = jnp.take_along_axis(
        logits, safe_tgt[:, None], axis=-1
    )[:, 0]                                                     # [chunk]
    token_loss = jnp.where(valid, lse - tgt_logit, 0.0)
    return token_loss.sum(), valid.sum().astype(jnp.float32)


def fused_cross_entropy(
    hidden: jax.Array,            # [batch, seq, d] (or [tokens, d])
    kernel: jax.Array,            # [d, vocab]
    targets: jax.Array,           # [batch, seq] (or [tokens]) int; < 0 ignored
    *,
    chunk_size: int = 512,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Mean softmax cross-entropy over valid tokens, logits never stored.

    Equivalent to
    ``optax.softmax_cross_entropy_with_integer_labels(hidden @ kernel, targets)``
    masked-mean'd, to f32 accuracy of the bf16 matmul.
    """
    d = hidden.shape[-1]
    x = hidden.reshape(-1, d)
    tgt = targets.reshape(-1)
    n = x.shape[0]

    pad = (-n) % chunk_size
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)], axis=0)
        tgt = jnp.concatenate([tgt, jnp.full((pad,), -1, tgt.dtype)], axis=0)
    num_chunks = x.shape[0] // chunk_size
    x = x.reshape(num_chunks, chunk_size, d)
    tgt = tgt.reshape(num_chunks, chunk_size)

    body = jax.checkpoint(
        partial(_chunk_loss, compute_dtype=compute_dtype), prevent_cse=False
    )

    def scan_step(carry, chunk):
        loss_sum, count = carry
        xs, ts = chunk
        s, c = body(xs, kernel, ts)
        return (loss_sum + s, count + c), None

    (loss_sum, count), _ = jax.lax.scan(
        scan_step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (x, tgt)
    )
    return loss_sum / jnp.maximum(count, 1.0)


def naive_cross_entropy(
    hidden: jax.Array, kernel: jax.Array, targets: jax.Array
) -> jax.Array:
    """Reference implementation (materializes logits); used by tests."""
    logits = jnp.dot(hidden, kernel).astype(jnp.float32)
    valid = targets >= 0
    safe = jnp.where(valid, targets, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, lse - tgt, 0.0)
    return per_tok.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
