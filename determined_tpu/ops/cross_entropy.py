"""Fused blocked cross-entropy: lm_head matmul + softmax-CE without ever
materializing the full ``[tokens, vocab]`` logits in HBM.

Motivation (TPU): with V=32k vocab and f32 logits, the standard
``logits = x @ W; softmax_xent(logits)`` pattern writes B*S*V*4 bytes to HBM
and reads them back in the backward pass — ~1 GiB per step at d2048/s1024/b8
— which is pure bandwidth waste on a bandwidth-bound chip (BASELINE.md: the
bs16 step *regresses* because of it).  Here the token dimension is scanned
in chunks: each chunk computes its logits tile in bf16 on the MXU, reduces
to per-token loss in f32, and the tile dies in VMEM/registers.
``jax.checkpoint`` on the chunk body makes the backward pass recompute the
tile instead of storing it, so the only HBM traffic is x, W, and the scan
carry.  The extra recompute is one lm_head matmul (<5% of model FLOPs); the
saved traffic is the whole logits tensor, twice.

The reference has no analog (loss math lives in user pytorch code); this is
TPU-native design per SURVEY §7 hard-part (e).

Sharding: hidden is batch-sharded (dp/fsdp), the kernel may be
vocab-sharded (tp).  Everything here is plain jnp under jit, so XLA inserts
the psum for the vocab-sharded logsumexp per chunk.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _chunk_loss(
    x_chunk: jax.Array,       # [chunk, d]
    kernel: jax.Array,        # [d, vocab]
    tgt_chunk: jax.Array,     # [chunk] int; < 0 = ignore
    compute_dtype,
    return_internals: bool = False,
):
    """Sum of token losses + valid-token count for one chunk.

    ``return_internals`` additionally returns (logits, lse) — the
    bf16-residual custom VJP shares this exact forward math so the two
    paths cannot drift.
    """
    logits = jnp.dot(
        x_chunk.astype(compute_dtype),
        kernel.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )  # [chunk, vocab] f32 accumulate on the MXU, lives only inside the chunk
    valid = tgt_chunk >= 0
    safe_tgt = jnp.where(valid, tgt_chunk, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)                     # [chunk]
    tgt_logit = jnp.take_along_axis(
        logits, safe_tgt[:, None], axis=-1
    )[:, 0]                                                     # [chunk]
    token_loss = jnp.where(valid, lse - tgt_logit, 0.0)
    loss_sum = token_loss.sum()
    count = valid.sum().astype(jnp.float32)
    if return_internals:
        return loss_sum, count, logits, lse
    return loss_sum, count


# ---------------------------------------------------------------------------
# bf16-residual single tile: the backward pass reconstructs softmax probs
# from a BF16 copy of the logits instead of the f32 tile autodiff would
# keep.  Halves the residual's HBM traffic (write + 2 reads of ~1 GiB at
# the flagship shape, measured ~+0.01 MFU) at the cost of ~bf16-epsilon
# relative error on the lm_head gradient — opt-in for that reason.
# ---------------------------------------------------------------------------


@jax.custom_vjp
def _tile_ce_bf16_residual(x, kernel, tgt):
    loss_sum, count = _chunk_loss(x, kernel, tgt, jnp.bfloat16)
    return loss_sum, count


def _tile_ce16_fwd(x, kernel, tgt):
    loss_sum, count, logits, lse = _chunk_loss(
        x, kernel, tgt, jnp.bfloat16, return_internals=True
    )
    # the ONLY tensor-sized residual is the bf16 logits copy
    return (loss_sum, count), (x, kernel, tgt, logits.astype(jnp.bfloat16), lse)


def _tile_ce16_bwd(res, g):
    x, kernel, tgt, logits16, lse = res
    g_loss, _ = g  # count is a constant wrt inputs
    valid = tgt >= 0
    safe_tgt = jnp.where(valid, tgt, 0)
    # all elementwise (iota-compare instead of a scatter) so XLA fuses the
    # whole dlogits computation into the two consumer matmuls — nothing
    # f32 tensor-sized materializes
    cols = jax.lax.broadcasted_iota(jnp.int32, logits16.shape, 1)
    p = jnp.exp(logits16.astype(jnp.float32) - lse[:, None])    # [n, vocab]
    dlogits = p - (cols == safe_tgt[:, None]).astype(jnp.float32)
    dlogits = jnp.where(valid[:, None], dlogits, 0.0) * g_loss
    d16 = dlogits.astype(jnp.bfloat16)
    dx = jnp.dot(
        d16, kernel.astype(jnp.bfloat16).T, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    dk = jnp.dot(
        x.astype(jnp.bfloat16).T, d16, preferred_element_type=jnp.float32
    ).astype(kernel.dtype)
    return dx, dk, None


_tile_ce_bf16_residual.defvjp(_tile_ce16_fwd, _tile_ce16_bwd)


def fused_cross_entropy(
    hidden: jax.Array,            # [batch, seq, d] (or [tokens, d])
    kernel: jax.Array,            # [d, vocab]
    targets: jax.Array,           # [batch, seq] (or [tokens]) int; < 0 ignored
    *,
    chunk_size: Optional[int] = None,
    compute_dtype=jnp.bfloat16,
    batch_shards: int = 1,
    bf16_residual: bool = False,
) -> jax.Array:
    """Mean softmax cross-entropy over valid tokens.

    Equivalent to
    ``optax.softmax_cross_entropy_with_integer_labels(hidden @ kernel, targets)``
    masked-mean'd, to f32 accuracy of the bf16 matmul.  Two modes:

    - single tile (``chunk_size=0``): one bf16 matmul with f32 accumulation;
      autodiff keeps the f32 logits tile as a backward residual (no
      recompute) — fastest when that residual fits (measured +1.2 MFU pts
      at d2048/V32k/8k tokens on v5e);
    - chunked scan (``chunk_size=N``): ``jax.checkpoint`` per chunk, so NO
      logits tensor survives to the backward pass — the long-context /
      huge-batch mode (caps live memory at chunk x vocab).

    ``chunk_size=None`` picks by the PER-SHARD f32 residual size
    (``batch_shards`` = product of batch-sharding mesh axes: under dp the
    tile is sharded, so the global token count overstates it).
    """
    d = hidden.shape[-1]
    x = hidden.reshape(-1, d)
    tgt = targets.reshape(-1)
    n = x.shape[0]

    # the bf16-residual path is a single-tile variant whose fwd matmul is
    # bf16 by construction; honoring it under f32 compute would degrade
    # the forward loss beyond the documented backward-only tradeoff
    bf16_residual = bf16_residual and compute_dtype == jnp.bfloat16

    if chunk_size is None:
        vocab = kernel.shape[-1]
        # backward residual per batch shard in single-tile mode: f32
        # logits by default, a bf16 copy under bf16_residual
        bytes_per = 2 if bf16_residual else 4
        tile_bytes = n * vocab * bytes_per // max(batch_shards, 1)
        # measured on v5e (d2048/L8/V32k): 1GB residual (8k tokens) is
        # fastest; 2GB (16k tokens) loses to the scan's remat
        chunk_size = 0 if tile_bytes <= (3 << 29) else 4096

    if chunk_size <= 0:
        # single-tile is an explicit opt-in (or auto pick): no remat, the
        # f32 logits tile survives as a backward residual.  An explicit
        # chunk_size >= n still runs the remat'd scan with one chunk —
        # callers who asked for chunking asked for the memory guarantee.
        if bf16_residual:
            loss_sum, count = _tile_ce_bf16_residual(x, kernel, tgt)
        else:
            loss_sum, count = _chunk_loss(x, kernel, tgt, compute_dtype)
        return loss_sum / jnp.maximum(count, 1.0)
    chunk_size = min(chunk_size, n)

    pad = (-n) % chunk_size
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, d), x.dtype)], axis=0)
        tgt = jnp.concatenate([tgt, jnp.full((pad,), -1, tgt.dtype)], axis=0)
    num_chunks = x.shape[0] // chunk_size
    x = x.reshape(num_chunks, chunk_size, d)
    tgt = tgt.reshape(num_chunks, chunk_size)

    body = jax.checkpoint(
        partial(_chunk_loss, compute_dtype=compute_dtype), prevent_cse=False
    )

    def scan_step(carry, chunk):
        loss_sum, count = carry
        xs, ts = chunk
        s, c = body(xs, kernel, ts)
        return (loss_sum + s, count + c), None

    (loss_sum, count), _ = jax.lax.scan(
        scan_step, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (x, tgt)
    )
    return loss_sum / jnp.maximum(count, 1.0)


def naive_cross_entropy(
    hidden: jax.Array, kernel: jax.Array, targets: jax.Array
) -> jax.Array:
    """Reference implementation (materializes logits); used by tests."""
    logits = jnp.dot(hidden, kernel).astype(jnp.float32)
    valid = targets >= 0
    safe = jnp.where(valid, targets, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, lse - tgt, 0.0)
    return per_tok.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
