"""Flash attention: Pallas TPU kernel, forward + custom-VJP backward.

Blockwise softmax attention (FlashAttention-2 style) tiled for the MXU:
O(seq) memory, no [Sq, Sk] materialization.  f32 accumulation in VMEM
scratch regardless of input dtype (bf16 inputs recommended).

Layout: q [b, h, Sq, d]; k, v [b, h_kv, Sk, d] (GQA: h_kv divides h —
expanded in the wrapper, gradients re-reduced over the group).

Grid: (batch, heads, q_blocks, k_blocks), k innermost; running (m, l, acc)
live in VMEM scratch across the k sweep.  Causal blocks strictly above the
diagonal are skipped with ``pl.when`` (half the FLOPs at long seq).

On non-TPU backends the kernel runs in interpreter mode (tests on the
8-device CPU mesh exercise the exact same code path).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Measured on v5e (hd=128, bf16): 1024-blocks run the fwd+bwd sweep ~3.7x
# faster than 128-blocks (36 vs 10 TFLOP/s at seq 1k, 49 vs 12 at seq 4k) —
# fewer grid steps amortize the VMEM (m,l,acc) rescale between MXU calls,
# and [1024,1024] logit tiles still fit VMEM comfortably.
DEFAULT_BLOCK = 1024

# The softmax runs in log2 space: the qk dot is scaled by scale*log2(e)
# once (MXU output epilogue) and every exp becomes a native exp2 — on TPU
# `exp` lowers to exp2 + a per-element multiply, so log2 space deletes one
# VPU multiply per logit from the kernel's bound resource (the VPU).  The
# stored lse is base-2 (m + log2 l), consumed only by the bwd kernels.
LOG2E = 1.4426950408889634


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(seq: int, want: int) -> int:
    block = min(want, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _scores(q_ref, k_ref, qi, ki, scale, causal, block_q, block_k):
    """qk dot in log2 space (scale*log2e folded into the MXU epilogue) +
    causal mask.  Shared by the fwd and both bwd kernels so the three
    stay bit-identical on the p they reconstruct."""
    q = q_ref[0, 0]                                   # [bq, d]
    k = k_ref[0, 0]                                   # [bk, d]
    s2 = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * LOG2E)                               # [bq, bk] f32, log2 units
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s2 = jnp.where(q_pos >= k_pos, s2, NEG_INF)
    return s2


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: process only blocks touching/below the diagonal
    needed = True if not causal else (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(needed)
    def _compute():
        # MXU inputs stay in the INPUT dtype (bf16 in production: ~4x the
        # f32 matmul throughput on v5e) with f32 accumulation; only the
        # softmax running stats are f32.  f32 inputs (tests/debug) keep
        # full f32 matmuls, so tight-tolerance checks still hold.
        q = q_ref[0, 0]                               # [bq, d]
        v = v_ref[0, 0]                               # [bk, d]
        s2 = _scores(q_ref, k_ref, qi, ki, scale, causal, block_q, block_k)
        m_prev, l_prev = m_sc[:], l_sc[:]
        m_cur = jnp.max(s2, axis=1, keepdims=True)    # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s2 - m_new)                      # [bq, bk] f32
        alpha = jnp.exp2(m_prev - m_new)              # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
            p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:] = m_new
        l_sc[:] = l_new

    @pl.when(ki == nk - 1)
    def _final():
        l = jnp.maximum(l_sc[:], 1e-30)
        o_ref[0, 0] = (acc_sc[:] / l).astype(o_ref.dtype)
        # lse is laid out [b, h, 1, sq] so the block's last dim is the
        # 128-aligned seq dim (TPU block-shape constraint)
        lse_ref[0, 0] = (m_sc[:] + jnp.log2(l))[:, 0][None, :]


def _fwd_kernel_single(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    """nk == 1 specialization: the whole k sweep is one block, so the
    online-softmax machinery (running m/l scratch, acc rescale, the init
    and final grid phases) is pure VPU overhead — a plain one-pass softmax
    does the same math with none of it.  This is the hot shape: the
    flagship seq-1024 workload runs block 1024 (see DEFAULT_BLOCK note)."""
    qi, ki = pl.program_id(2), pl.program_id(3)
    q = q_ref[0, 0]
    v = v_ref[0, 0]
    s2 = _scores(q_ref, k_ref, qi, ki, scale, causal, block_q, block_k)
    m = jnp.max(s2, axis=1, keepdims=True)            # [bq, 1]
    p = jnp.exp2(s2 - m)                              # [bq, bk] f32
    l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
    acc = jax.lax.dot_general(
        p.astype(q.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log2(l))[:, 0][None, :]


def _flash_fwd_call(
    q: jax.Array, k: jax.Array, v: jax.Array, scale: float, causal: bool,
    block_q: int, block_k: int,
) -> Tuple[jax.Array, jax.Array]:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k
    grid = (b, h, nq, nk)
    single = nk == 1
    kernel = functools.partial(
        _fwd_kernel_single if single else _fwd_kernel,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        ],
        scratch_shapes=[] if single else [
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    needed = True if not causal else (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(needed)
    def _compute():
        # bf16 MXU inputs, f32 accumulation (see _fwd_kernel note)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)            # [bq, 1], log2 units
        delta = delta_ref[0, 0].reshape(-1, 1)        # [bq, 1]
        s2 = _scores(q_ref, k_ref, qi, ki, scale, causal, block_q, block_k)
        p = jnp.exp2(s2 - lse)                        # [bq, bk] f32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                             # [bq, bk] f32
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_sc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    ki, qi = pl.program_id(2), pl.program_id(3)       # NOTE: q innermost here
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    needed = True if not causal else (qi * block_q + block_q - 1 >= ki * block_k)

    @pl.when(needed)
    def _compute():
        # bf16 MXU inputs, f32 accumulation (see _fwd_kernel note)
        q = q_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)            # log2 units
        delta = delta_ref[0, 0].reshape(-1, 1)
        s2 = _scores(q_ref, k_ref, qi, ki, scale, causal, block_q, block_k)
        p = jnp.exp2(s2 - lse)                        # [bq, bk] f32
        p_in = p.astype(q.dtype)
        dv_sc[:] += jax.lax.dot_general(
            p_in, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                             # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)  # [bq, bk]
        dk_sc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                             # [bk, d]

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_bwd_call(
    q, k, v, do, out, lse, scale, causal, block_q, block_k
):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k
    # delta = rowsum(do * out): tiny elementwise+reduce, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[
        :, :, None, :
    ]  # [b, h, 1, sq] — same layout as lse

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0))
    rowq = pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, qi, ki: (bi, hi, 0, qi))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=(b, h, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dkv sweep: swap loop nest — k blocks outer, q inner
    qspec2 = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kspec2 = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    rowq2 = pl.BlockSpec((1, 1, 1, block_q), lambda bi, hi, ki, qi: (bi, hi, 0, qi))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=(b, h, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    out, _ = _flash_fwd_call(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd_call(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd_call(q, k, v, g, out, lse, scale, causal, block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
) -> jax.Array:
    """Blockwise flash attention; differentiable; GQA-aware."""
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    from determined_tpu.ops.attention import _repeat_kv

    n_rep = h // hkv
    # expand kv for the kernel; group-sum of dk/dv happens automatically
    # through the broadcast's transpose in autodiff
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    return _flash(q, k, v, scale, causal, block_q, block_k)
