"""Fused AdamW + global-norm clip: one read-modify-write sweep over HBM.

Motivation (BASELINE.md r3 roofline): the optax ``chain(clip_by_global_norm,
adamw)`` step is bandwidth-bound at ~9 HBM passes over param-sized arrays
(~26 ms of the 231 ms headline step).  The information-theoretic floor is
7 passes — read p, m, v, g; write p, m, v — plus one read of g for the
global norm.  This module hits that floor with a single Pallas kernel per
(large) leaf:

- clip scale, learning rate, and Adam bias corrections enter as SMEM
  scalars; b1/b2/eps/weight_decay are compile-time constants;
- ``input_output_aliases`` makes the p/m/v updates in-place (the Trainer
  donates the whole TrainState, so XLA reuses the buffers);
- optional bf16 first moment (``mu_dtype``) halves that leaf's traffic with
  the conversion fused into the same pass — the standalone-conversion cost
  that made optax's ``mu_dtype=bf16`` a loss (r3) does not exist here;
- small leaves (norm scales, biases) take the plain-jnp path: their traffic
  is negligible and padding them to kernel tiles would waste more than it
  saves.

The reference has no analog (optimizers live in torch userland); this is
the TPU-native answer to SURVEY §7's "optimizer at the bandwidth roofline"
hard part.  Semantics match ``optax.chain(clip_by_global_norm(c),
adamw(lr, b1, b2, eps, weight_decay=wd, mu_dtype=...))`` exactly
(verified by ``tests/test_ops.py::test_fused_adamw*``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

# Per-ref block budget.  7 refs (p/m/v/g in, p/m/v out) x double-buffered
# must fit the 16 MiB scoped-VMEM budget; 1 MiB blocks measured 16.84M > 16M
# on v5e (OOM), 768 KiB measured fastest of {512K, 768K}.
_BLOCK_BYTES = 768 * 1024


def _min_pallas_size() -> int:
    """Leaves below this ride the jnp path (one big XLA fusion, near-zero
    launch overhead); leaves above it get their own Pallas sweep.

    The r4 xplane accounting measured ~120 us of fixed per-call overhead x
    34 sweeps ≈ 4 ms/step — most of the fused kernel's saved HBM pass.
    The in-kernel bandwidth edge of Pallas over a well-fused XLA update is
    small, so small/mid leaves are better off batched into XLA's fusion;
    only leaves whose sweep time dwarfs the launch overhead keep their own
    call.  Measured sweep on the v5e chip (BASELINE.md r5): 256K (34
    calls) 0.693 MFU, 4M 0.696, 8M 0.699-0.701, 16M 0.701, 32M (2 calls)
    0.698, pure-jnp 0.688 — 8M default = embed/lm_head (67M) + the 24
    16M swiglu leaves, 26 Pallas calls.  DTPU_FUSED_MIN_SIZE overrides.
    """
    import os

    return int(os.environ.get("DTPU_FUSED_MIN_SIZE", 8 * 1024 * 1024))


class FusedAdamWState(NamedTuple):
    count: jax.Array  # int32 step counter
    mu: Any           # first moment (param dtype or mu_dtype)
    nu: Any           # second moment (f32)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _adamw_kernel(b1, b2, eps, wd, scal_ref, p_ref, m_ref, v_ref, g_ref,
                  po_ref, mo_ref, vo_ref):
    lr = scal_ref[0, 0]
    cs = scal_ref[0, 1]     # global-clip scale
    bc1 = scal_ref[0, 2]    # 1 - b1^t
    bc2 = scal_ref[0, 3]    # 1 - b2^t
    g = g_ref[...].astype(jnp.float32) * cs
    m = m_ref[...].astype(jnp.float32) * b1 + g * (1.0 - b1)
    v = v_ref[...] * b2 + g * g * (1.0 - b2)
    mhat = m / bc1
    vhat = v / bc2
    p = p_ref[...]
    update = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    po_ref[...] = p - lr * update
    mo_ref[...] = m.astype(mo_ref.dtype)
    vo_ref[...] = v


def _plan_blocks(shape):
    """(grid, block) tiling a leaf IN ITS NATIVE SHAPE, or None to fall
    back to jnp.  Native-shape blocks are the point: flatten/reshape
    changes the TPU tiled layout and XLA then physically copies every
    operand around the kernel — the flattened first cut of this kernel
    measured ~3x slower than optax purely from those copies.

    2D leaves tile both dims (wide lm_head/vocab arrays need a column
    split to keep >=8 rows per block); 3D+ leaves keep trailing dims whole
    and split the leading dim.  All dims here are powers of two.
    """
    import math

    budget = _BLOCK_BYTES // 4  # f32 elements per ref
    d0, dk = shape[0], shape[-1]
    mid = math.prod(shape[1:-1]) if len(shape) > 2 else 1
    # block's last two dims must be (multiple of 8, multiple of 128) or the
    # full dims; middle dims stay whole, first + last split to fit budget
    br_min = 8 if len(shape) == 2 else 1
    if d0 % br_min:
        return None
    bc = dk
    while bc % 2 == 0 and bc > 128 and br_min * mid * bc > budget:
        bc //= 2
    if bc != dk and bc % 128:
        return None
    br = br_min
    while br * 2 * mid * bc <= budget and d0 % (br * 2) == 0:
        br *= 2
    if br * mid * bc > budget:
        return None  # middle dims alone exceed the budget: jnp fallback
    return (d0 // br, dk // bc), (br,) + tuple(shape[1:-1]) + (bc,)


def _leaf_pallas(p, m, v, g, scalars, *, b1, b2, eps, wd):
    """One fused sweep over a large leaf in its native shape."""
    from jax.experimental import pallas as pl

    grid, block = _plan_blocks(p.shape)
    zeros = (0,) * (p.ndim - 2)
    index_map = lambda i, j: (i,) + zeros + (j,)  # noqa: E731
    scal_map = lambda i, j: (0, 0)  # noqa: E731
    bspec = lambda: pl.BlockSpec(block, index_map)  # noqa: E731
    po, mo, vo = pl.pallas_call(
        partial(_adamw_kernel, b1, b2, eps, wd),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 4), scal_map),  # scalars ride along
            bspec(), bspec(), bspec(), bspec(),
        ],
        out_specs=[bspec(), bspec(), bspec()],
        out_shape=[
            jax.ShapeDtypeStruct(p.shape, p.dtype),
            jax.ShapeDtypeStruct(m.shape, m.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        # in-place p/m/v (argument order: scalars, p, m, v, g)
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=_interpret(),
    )(scalars, p, m, v, g)
    return po, mo, vo


def _leaf_jnp(p, m, v, g, scalars, *, b1, b2, eps, wd):
    lr, cs, bc1, bc2 = (scalars[0, i] for i in range(4))
    gf = g.astype(jnp.float32) * cs
    m_new = m.astype(jnp.float32) * b1 + gf * (1.0 - b1)
    v_new = v * b2 + gf * gf * (1.0 - b2)
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p
    return p - lr * update, m_new.astype(m.dtype), v_new


@dataclasses.dataclass(frozen=True)
class FusedAdamW:
    """Full-step fused optimizer.  ``apply_step`` consumes grads and returns
    (new_params, new_state) directly — no separate "updates" tree, which is
    the point: materializing updates costs two extra HBM passes."""

    learning_rate: Union[float, Callable[[jax.Array], jax.Array]]
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.01
    clip_norm: Optional[float] = 1.0
    mu_dtype: Optional[Any] = None

    def init(self, params: Any) -> FusedAdamWState:
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=self.mu_dtype or p.dtype), params
        )
        nu = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return FusedAdamWState(jnp.zeros((), jnp.int32), mu, nu)

    def _scalars(self, count: jax.Array, grads: Any) -> jax.Array:
        t = (count + 1).astype(jnp.float32)
        lr = self.learning_rate(count) if callable(self.learning_rate) else self.learning_rate
        if self.clip_norm is not None:
            gn = optax.global_norm(grads)
            cs = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gn, 1e-16))
        else:
            cs = jnp.ones(())
        bc1 = 1.0 - self.b1 ** t
        bc2 = 1.0 - self.b2 ** t
        return jnp.stack([jnp.asarray(lr, jnp.float32), cs.astype(jnp.float32),
                          bc1, bc2]).reshape(1, 4)

    def apply_step(self, grads: Any, state: FusedAdamWState, params: Any):
        scalars = self._scalars(state.count, grads)
        kw = dict(b1=self.b1, b2=self.b2, eps=self.eps, wd=self.weight_decay)

        min_size = _min_pallas_size()

        def leaf(p, m, v, g):
            if (
                p.size >= min_size
                and p.dtype == jnp.float32
                and p.ndim >= 2
                and _plan_blocks(p.shape) is not None
            ):
                return _leaf_pallas(p, m, v, g, scalars, **kw)
            return _leaf_jnp(p, m, v, g, scalars, **kw)

        out = jax.tree.map(leaf, params, state.mu, state.nu, grads)
        # out leaves are (p, m, v) triples; re-split into three trees
        is_triple = lambda x: isinstance(x, tuple) and len(x) == 3  # noqa: E731
        new_p = jax.tree.map(lambda t: t[0], out, is_leaf=is_triple)
        new_m = jax.tree.map(lambda t: t[1], out, is_leaf=is_triple)
        new_v = jax.tree.map(lambda t: t[2], out, is_leaf=is_triple)
        return new_p, FusedAdamWState(state.count + 1, new_m, new_v)

    # optax-compatible shim (not used by the Trainer's fused path): returns
    # an updates tree; costs the extra passes the fused path avoids
    def update(self, grads: Any, state: FusedAdamWState, params: Any):
        new_p, new_state = self.apply_step(grads, state, params)
        updates = jax.tree.map(lambda a, b: a - b, new_p, params)
        return updates, new_state


def fused_adamw(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.01,
    clip_norm: Optional[float] = 1.0,
    mu_dtype: Optional[Any] = None,
) -> FusedAdamW:
    return FusedAdamW(
        learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, clip_norm=clip_norm, mu_dtype=mu_dtype,
    )
