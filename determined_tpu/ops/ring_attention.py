"""Ring attention: sequence-parallel attention over the mesh "seq" axis.

Long-context machinery the reference platform lacks entirely (SURVEY.md
§2.10: "SP / CP / ring attention ... not present").  Design follows the
blockwise-parallel / ring-attention construction: q, k, v are sharded along
the sequence dim across the "seq" mesh axis; each device computes blockwise
attention of its local queries against the k/v shard it currently holds,
maintaining a running (m, l, acc) softmax state, then passes the k/v shard
to its ring neighbor with ``lax.ppermute`` (XLA lowers this to ICI
neighbor exchanges that overlap with the block compute).

Memory per device is O(S/N) in BOTH directions: the backward is a custom
VJP that re-runs the ring, rotating (k, v, dk, dv) together so no per-step
k/v residuals are stored (a plain autodiff through the scan would stash
every rotated shard = O(S) per device).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 moved shard_map to jax.shard_map
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from determined_tpu.ops.attention import _repeat_kv
from determined_tpu.parallel.mesh import MeshAxes

NEG_INF = -1e30


def _block_logits(q, k, scale, causal, q_start, k_start, sl):
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = q_start + jnp.arange(sl)[:, None]
        k_pos = k_start + jnp.arange(sl)[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s


def _ring_fwd_local(q, k, v, *, axis_name, causal, scale):
    """Forward ring sweep; returns (out, lse) with local seq shards."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc = jnp.zeros((b, h, sl, d), jnp.float32)

    def step_fn(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (idx - step) % n
        s = _block_logits(qf, k_cur, scale, causal, idx * sl, src * sl, sl)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(step_fn, (m, l, acc, k, v), jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = (acc / l).astype(q.dtype)
    lse = m + jnp.log(l)  # [b, h, sl, 1]
    return out, lse


def _ring_bwd_local(q, k, v, out, lse, do, *, axis_name, causal, scale):
    """Backward ring sweep: dk/dv rotate WITH their k/v shards, arriving
    home after n steps; no per-step residuals are kept."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq = jnp.zeros((b, h, sl, d), jnp.float32)
    dk = jnp.zeros_like(k, dtype=jnp.float32)
    dv = jnp.zeros_like(v, dtype=jnp.float32)

    def step_fn(carry, step):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - step) % n
        s = _block_logits(qf, k_cur, scale, causal, idx * sl, src * sl, sl)
        p = jnp.exp(s - lse)                                  # [b,h,ql,kl]
        dp = jnp.einsum(
            "bhqd,bhkd->bhqk", dof, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        dk_cur = dk_cur + jnp.einsum(
            "bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32
        )
        dv_cur = dv_cur + jnp.einsum(
            "bhqk,bhqd->bhkd", p, dof, preferred_element_type=jnp.float32
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step_fn, (dq, k, v, dk, dv), jnp.arange(n)
    )
    # after n rotations dk/dv have completed a full loop and are home
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_local(q, k, v, axis_name, causal, scale):
    out, _ = _ring_fwd_local(q, k, v, axis_name=axis_name, causal=causal, scale=scale)
    return out


def _ring_local_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_local(q, k, v, axis_name=axis_name, causal=causal, scale=scale)
    return out, (q, k, v, out, lse)


def _ring_local_bwd(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    return _ring_bwd_local(
        q, k, v, out, lse, g, axis_name=axis_name, causal=causal, scale=scale
    )


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = MeshAxes.SEQUENCE,
) -> jax.Array:
    """Sequence-parallel attention over global [b, h, S, d] arrays.

    Batch dim may additionally be sharded over data/fsdp axes and heads over
    the tensor axis; the seq dim is sharded over ``seq_axis``.  GQA kv heads
    are expanded before the ring (gradient re-reduction over the group comes
    from the broadcast's transpose).  Falls back to single-shard blockwise
    attention when the mesh has no seq axis.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    n_rep = q.shape[1] // k.shape[1]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)

    if mesh.shape.get(seq_axis, 1) <= 1:
        from determined_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale)

    batch_axes = tuple(
        a for a in (MeshAxes.DATA, MeshAxes.FSDP) if mesh.shape.get(a, 1) > 1
    )
    head_axis = MeshAxes.TENSOR if mesh.shape.get(MeshAxes.TENSOR, 1) > 1 else None
    spec = P(batch_axes or None, head_axis, seq_axis, None)

    fn = shard_map(
        lambda q, k, v: _ring_local(q, k, v, seq_axis, causal, scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
