"""Ring attention: sequence-parallel attention over the mesh "seq" axis.

Long-context machinery the reference platform lacks entirely (SURVEY.md
§2.10: "SP / CP / ring attention ... not present").  Design follows the
blockwise-parallel / ring-attention construction: q, k, v are sharded along
the sequence dim across the "seq" mesh axis; each device computes blockwise
attention of its local queries against the k/v shard it currently holds,
maintaining a running (m, l, acc) softmax state, then passes the k/v shard
to its ring neighbor with ``lax.ppermute`` (XLA lowers this to ICI
neighbor exchanges that overlap with the block compute).

Efficiency notes:
- **Causal work balancing (zigzag assignment)**: under causal masking with
  CONTIGUOUS sequence shards, rank r's queries attend to r+1 of the n k/v
  shards — the last rank does n times the work of the first and sets the
  critical path, so skipping masked blocks saves FLOPs/energy but no
  wall-clock.  The ``zigzag`` assignment (the llama3-style context-parallel
  trick) gives every rank one LOW half-chunk (chunk r) and one HIGH
  half-chunk (chunk 2n-1-r) of the sequence, so each rank executes exactly
  2 half-block computes per ring step (3 on its diagonal step) — balanced,
  and ~half the FLOPs of the dense sweep on the critical path.  The
  conversion between the contiguous layout outside and the zigzag layout
  inside is two half-chunk ``ppermute``s on entry/exit (O(S/n) bytes vs the
  ring's O(S) total, so the fix-up is amortized away).  Contiguous remains
  the path for non-causal attention, where work is already balanced.
  Per-rank executed-work counters (``ring_block_counts``) make the balance
  testable without relying on noisy CPU-emulated wall-clock.
- **Causal step skipping**: a k/v (half-)shard lying strictly after the
  local queries contributes nothing under causal masking; those computes
  are skipped with ``lax.cond`` (the rotation still happens).
- **Grouped-KV rotation**: with GQA the ring rotates the *kv* heads and
  expands to full heads only inside the local block compute, dividing
  ppermute/ICI traffic by the group size; dk/dv are group-summed back
  before they continue around the ring.  (When the tensor axis does not
  divide h_kv, k/v are pre-expanded instead so head sharding stays legal.)

Memory per device is O(S/N) in BOTH directions: the backward is a custom
VJP that re-runs the ring, rotating (k, v, dk, dv) together so no per-step
k/v residuals are stored (a plain autodiff through the scan would stash
every rotated shard = O(S) per device).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from determined_tpu.ops.attention import _repeat_kv
from determined_tpu.parallel._compat import axis_size, shard_map
from determined_tpu.parallel.mesh import MeshAxes

NEG_INF = -1e30


def _block_logits(q, k, scale, causal, q_pos, k_pos):
    """Masked logits for one block; ``q_pos``/``k_pos`` are global position
    vectors (contiguous or zigzag — the mask only sees positions)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# contiguous assignment (non-causal path + fallback)
# ---------------------------------------------------------------------------


def _ring_fwd_local(q, k, v, *, axis_name, causal, scale, n_rep):
    """Forward ring sweep; returns (out, lse, cnt) with local seq shards.

    k/v carry ``h_kv`` heads around the ring; expansion to the full head
    count happens per step inside the block compute.  ``cnt`` counts
    executed half-block-equivalents (each full-shard compute = 4); the
    increments live inside the cond branches, so the counter reports
    what actually ran (``ring_block_counts`` surfaces it; the vjp
    wrappers drop it).
    """
    n = axis_size(axis_name)
    # positions (and the rank index feeding them) exist only for the causal
    # mask; on the non-causal path axis_index must not be emitted at all —
    # its dead value survives into the custom_vjp residual jaxpr and older
    # XLA then refuses to SPMD-partition the PartitionId instruction
    idx = jax.lax.axis_index(axis_name) if causal else 0
    b, h, sl, d = q.shape
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), jnp.float32)
    cnt0 = jnp.zeros((), jnp.int32)

    def step_fn(carry, step):
        m, l, acc, cnt, k_cur, v_cur = carry
        src = (idx - step) % n

        def compute(m, l, acc, cnt):
            k_exp = _repeat_kv(k_cur, n_rep)
            v_exp = _repeat_kv(v_cur, n_rep)
            q_pos = (idx * sl + jnp.arange(sl)) if causal else None
            k_pos = (src * sl + jnp.arange(sl)) if causal else None
            s = _block_logits(qf, k_exp, scale, causal, q_pos, k_pos)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new, cnt + 4

        if causal:
            # src > idx: the shard lies strictly after every local query —
            # fully masked, skip the block compute entirely
            m, l, acc, cnt = jax.lax.cond(
                src <= idx, compute, lambda m, l, acc, cnt: (m, l, acc, cnt),
                m, l, acc, cnt,
            )
        else:
            m, l, acc, cnt = compute(m, l, acc, cnt)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, cnt, k_nxt, v_nxt), None

    (m, l, acc, cnt, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, acc0, cnt0, k, v), jnp.arange(n)
    )
    l = jnp.maximum(l, 1e-30)
    out = (acc / l).astype(q.dtype)
    lse = m + jnp.log(l)  # [b, h, sl, 1]
    return out, lse, cnt


def _ring_bwd_local(q, k, v, out, lse, do, *, axis_name, causal, scale, n_rep):
    """Backward ring sweep: dk/dv rotate WITH their k/v shards, arriving
    home after n steps; no per-step residuals are kept.  dk/dv travel with
    ``h_kv`` heads (group-summed from the expanded gradient each step)."""
    n = axis_size(axis_name)
    # see _ring_fwd_local: no dead axis_index on the non-causal path
    idx = jax.lax.axis_index(axis_name) if causal else 0
    b, h, sl, d = q.shape
    h_kv = k.shape[1]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0 = jnp.zeros((b, h, sl, d), jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)

    def step_fn(carry, step):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - step) % n

        def compute(dq, dk_cur, dv_cur):
            k_exp = _repeat_kv(k_cur, n_rep)
            v_exp = _repeat_kv(v_cur, n_rep)
            q_pos = (idx * sl + jnp.arange(sl)) if causal else None
            k_pos = (src * sl + jnp.arange(sl)) if causal else None
            s = _block_logits(qf, k_exp, scale, causal, q_pos, k_pos)
            p = jnp.exp(s - lse)                              # [b,h,ql,kl]
            dp = jnp.einsum(
                "bhqd,bhkd->bhqk", dof, v_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta) * scale
            dq_new = dq + jnp.einsum(
                "bhqk,bhkd->bhqd", ds, k_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            dk_full = jnp.einsum(
                "bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32
            )
            dv_full = jnp.einsum(
                "bhqk,bhqd->bhkd", p, dof, preferred_element_type=jnp.float32
            )
            # group-sum the expanded-head gradient back to kv heads
            dk_new = dk_cur + dk_full.reshape(b, h_kv, n_rep, sl, d).sum(axis=2)
            dv_new = dv_cur + dv_full.reshape(b, h_kv, n_rep, sl, d).sum(axis=2)
            return dq_new, dk_new, dv_new

        if causal:
            dq, dk_cur, dv_cur = jax.lax.cond(
                src <= idx,
                compute,
                lambda dq, dk_cur, dv_cur: (dq, dk_cur, dv_cur),
                dq, dk_cur, dv_cur,
            )
        else:
            dq, dk_cur, dv_cur = compute(dq, dk_cur, dv_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step_fn, (dq0, k, v, dk0, dv0), jnp.arange(n)
    )
    # after n rotations dk/dv have completed a full loop and are home
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_local(q, k, v, axis_name, causal, scale, n_rep):
    out, _, _ = _ring_fwd_local(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep
    )
    return out


def _ring_local_fwd(q, k, v, axis_name, causal, scale, n_rep):
    out, lse, _ = _ring_fwd_local(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep
    )
    return out, (q, k, v, out, lse)


def _ring_local_bwd(axis_name, causal, scale, n_rep, res, g):
    q, k, v, out, lse = res
    return _ring_bwd_local(
        q, k, v, out, lse, g,
        axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep,
    )


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


# ---------------------------------------------------------------------------
# zigzag assignment (balanced causal work)
# ---------------------------------------------------------------------------


def _zz_owner(chunk: int, n: int) -> int:
    """Zigzag owner of half-chunk ``chunk`` (of 2n): rank r holds (r, 2n-1-r)."""
    return chunk if chunk < n else 2 * n - 1 - chunk


def zigzag_redistribute(x, axis_name, inverse: bool = False):
    """Exchange half-chunks between contiguous and zigzag layouts along the
    second-to-last dim (inside manual SPMD over ``axis_name``).

    Contiguous rank r holds sequence chunks (2r, 2r+1); zigzag rank r holds
    (r, 2n-1-r).  Each rank's two chunks have opposite parity, so the moves
    decompose into exactly two ``ppermute``s — one carrying the even chunks,
    one the odd — plus a parity select on arrival.
    """
    n = axis_size(axis_name)
    if n == 1:
        return x
    sl = x.shape[-2]
    hc = sl // 2
    first, second = x[..., :hc, :], x[..., hc:, :]
    perm_a = [(s, _zz_owner(2 * s, n)) for s in range(n)]        # even chunks
    perm_b = [(s, _zz_owner(2 * s + 1, n)) for s in range(n)]    # odd chunks
    idx = jax.lax.axis_index(axis_name)
    even = (idx % 2) == 0
    if not inverse:
        ra = jax.lax.ppermute(first, axis_name, perm_a)
        rb = jax.lax.ppermute(second, axis_name, perm_b)
        # my zigzag chunks: (idx, 2n-1-idx) — idx shares my parity
        lo = jnp.where(even, ra, rb)
        hi = jnp.where(even, rb, ra)
        return jnp.concatenate([lo, hi], axis=-2)
    # inverse: send back what travelled each ppermute, along the inverse map
    send_a = jnp.where(even, first, second)     # the even chunk I hold
    send_b = jnp.where(even, second, first)     # the odd chunk I hold
    inv_a = [(d, s) for s, d in perm_a]
    inv_b = [(d, s) for s, d in perm_b]
    ra = jax.lax.ppermute(send_a, axis_name, inv_a)   # my chunk 2r
    rb = jax.lax.ppermute(send_b, axis_name, inv_b)   # my chunk 2r+1
    return jnp.concatenate([ra, rb], axis=-2)


def _zz_pos(rank, n, hc):
    """Global position vectors of the two half-chunks rank holds (zigzag)."""
    lo = rank * hc + jnp.arange(hc)
    hi = (2 * n - 1 - rank) * hc + jnp.arange(hc)
    return lo, hi


def _attn_update(qf, k_half, v_half, q_pos, k_pos, m, l, acc, scale, n_rep):
    """Online-softmax update of one q half against one k/v half-chunk."""
    k_exp = _repeat_kv(k_half, n_rep)
    v_exp = _repeat_kv(v_half, n_rep)
    s = _block_logits(qf, k_exp, scale, True, q_pos, k_pos)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_exp.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _zz_fwd_local(q, k, v, *, axis_name, scale, n_rep):
    """Zigzag causal forward.  Local shards are (lo, hi) half-chunks; per
    ring step each rank runs: hi-q × lo-k (always, fully unmasked),
    lo-q × lo-k (iff src ≤ idx), hi-q × hi-k (iff src ≥ idx) — so every
    rank executes 2 half-computes per step (3 on the diagonal), vs the
    contiguous sweep's rank-(n-1) doing 4 per step."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    hc = sl // 2
    q_lo = q[..., :hc, :].astype(jnp.float32)
    q_hi = q[..., hc:, :].astype(jnp.float32)
    p_lo, p_hi = _zz_pos(idx, n, hc)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def zero_state():
        return (
            jnp.full((b, h, hc, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, hc, 1), jnp.float32),
            jnp.zeros((b, h, hc, d), jnp.float32),
        )

    st_lo0, st_hi0 = zero_state(), zero_state()
    cnt0 = jnp.zeros((), jnp.int32)

    def step_fn(carry, step):
        st_lo, st_hi, cnt, k_cur, v_cur = carry
        src = (idx - step) % n
        k_lo, k_hi = k_cur[..., :hc, :], k_cur[..., hc:, :]
        v_lo, v_hi = v_cur[..., :hc, :], v_cur[..., hc:, :]
        kp_lo, kp_hi = _zz_pos(src, n, hc)

        # hi-q attends to every lo-k chunk: always computed, never masked
        st_hi = _attn_update(q_hi, k_lo, v_lo, p_hi, kp_lo, *st_hi, scale, n_rep)
        cnt = cnt + 1

        def lo_lo(st, cnt):
            m, l, acc = st
            return _attn_update(q_lo, k_lo, v_lo, p_lo, kp_lo, m, l, acc,
                                scale, n_rep), cnt + 1

        st_lo, cnt = jax.lax.cond(
            src <= idx, lo_lo, lambda st, cnt: (st, cnt), st_lo, cnt
        )

        def hi_hi(st, cnt):
            m, l, acc = st
            return _attn_update(q_hi, k_hi, v_hi, p_hi, kp_hi, m, l, acc,
                                scale, n_rep), cnt + 1

        st_hi, cnt = jax.lax.cond(
            src >= idx, hi_hi, lambda st, cnt: (st, cnt), st_hi, cnt
        )

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (st_lo, st_hi, cnt, k_nxt, v_nxt), None

    (st_lo, st_hi, cnt, _, _), _ = jax.lax.scan(
        step_fn, (st_lo0, st_hi0, cnt0, k, v), jnp.arange(n)
    )

    def finish(st):
        m, l, acc = st
        l = jnp.maximum(l, 1e-30)
        return (acc / l), m + jnp.log(l)

    out_lo, lse_lo = finish(st_lo)
    out_hi, lse_hi = finish(st_hi)
    out = jnp.concatenate([out_lo, out_hi], axis=-2).astype(q.dtype)
    lse = jnp.concatenate([lse_lo, lse_hi], axis=-2)
    return out, lse, cnt


def _attn_bwd_half(qf, k_half, v_half, lse_h, do_f, delta_h, q_pos, k_pos,
                   scale, n_rep, h_kv):
    """One (q-half, k-half) backward block: returns (dq, dk_grp, dv_grp)."""
    b, h, ql, d = qf.shape
    kl = k_half.shape[-2]
    k_exp = _repeat_kv(k_half, n_rep)
    v_exp = _repeat_kv(v_half, n_rep)
    s = _block_logits(qf, k_exp, scale, True, q_pos, k_pos)
    p = jnp.exp(s - lse_h)
    dp = jnp.einsum(
        "bhqd,bhkd->bhqk", do_f, v_exp.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_h) * scale
    dq = jnp.einsum(
        "bhqk,bhkd->bhqd", ds, k_exp.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    dk_full = jnp.einsum("bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32)
    dv_full = jnp.einsum("bhqk,bhqd->bhkd", p, do_f, preferred_element_type=jnp.float32)
    dk = dk_full.reshape(b, h_kv, h // h_kv, kl, d).sum(axis=2)
    dv = dv_full.reshape(b, h_kv, h // h_kv, kl, d).sum(axis=2)
    return dq, dk, dv


def _zz_bwd_local(q, k, v, out, lse, do, *, axis_name, scale, n_rep):
    """Zigzag causal backward: same balanced pair schedule as the forward;
    dk/dv rotate with their k/v shards and are home after n steps."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    hc = sl // 2
    h_kv = k.shape[1]
    q_lo = q[..., :hc, :].astype(jnp.float32)
    q_hi = q[..., hc:, :].astype(jnp.float32)
    do_f = do.astype(jnp.float32)
    delta = jnp.sum(do_f * out.astype(jnp.float32), axis=-1, keepdims=True)
    do_lo, do_hi = do_f[..., :hc, :], do_f[..., hc:, :]
    dl_lo, dl_hi = delta[..., :hc, :], delta[..., hc:, :]
    lse_lo, lse_hi = lse[..., :hc, :], lse[..., hc:, :]
    p_lo, p_hi = _zz_pos(idx, n, hc)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq_lo0 = jnp.zeros((b, h, hc, d), jnp.float32)
    dq_hi0 = jnp.zeros((b, h, hc, d), jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)

    def step_fn(carry, step):
        dq_lo, dq_hi, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - step) % n
        k_lo, k_hi = k_cur[..., :hc, :], k_cur[..., hc:, :]
        v_lo, v_hi = v_cur[..., :hc, :], v_cur[..., hc:, :]
        dk_lo, dk_hi = dk_cur[..., :hc, :], dk_cur[..., hc:, :]
        dv_lo, dv_hi = dv_cur[..., :hc, :], dv_cur[..., hc:, :]
        kp_lo, kp_hi = _zz_pos(src, n, hc)

        # hi-q × lo-k: always
        g = _attn_bwd_half(q_hi, k_lo, v_lo, lse_hi, do_hi, dl_hi,
                           p_hi, kp_lo, scale, n_rep, h_kv)
        dq_hi = dq_hi + g[0]
        dk_lo = dk_lo + g[1]
        dv_lo = dv_lo + g[2]

        def lo_lo(dq_lo, dk_lo, dv_lo):
            g = _attn_bwd_half(q_lo, k_lo, v_lo, lse_lo, do_lo, dl_lo,
                               p_lo, kp_lo, scale, n_rep, h_kv)
            return dq_lo + g[0], dk_lo + g[1], dv_lo + g[2]

        dq_lo, dk_lo, dv_lo = jax.lax.cond(
            src <= idx, lo_lo, lambda a, b_, c: (a, b_, c), dq_lo, dk_lo, dv_lo
        )

        def hi_hi(dq_hi, dk_hi, dv_hi):
            g = _attn_bwd_half(q_hi, k_hi, v_hi, lse_hi, do_hi, dl_hi,
                               p_hi, kp_hi, scale, n_rep, h_kv)
            return dq_hi + g[0], dk_hi + g[1], dv_hi + g[2]

        dq_hi, dk_hi, dv_hi = jax.lax.cond(
            src >= idx, hi_hi, lambda a, b_, c: (a, b_, c), dq_hi, dk_hi, dv_hi
        )

        dk_nxt = jnp.concatenate([dk_lo, dk_hi], axis=-2)
        dv_nxt = jnp.concatenate([dv_lo, dv_hi], axis=-2)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_nxt, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_nxt, axis_name, perm)
        return (dq_lo, dq_hi, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    (dq_lo, dq_hi, _, _, dk, dv), _ = jax.lax.scan(
        step_fn, (dq_lo0, dq_hi0, k, v, dk0, dv0), jnp.arange(n)
    )
    dq = jnp.concatenate([dq_lo, dq_hi], axis=-2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_local_zz(q, k, v, axis_name, scale, n_rep):
    out, _, _ = _zz_fwd_local(q, k, v, axis_name=axis_name, scale=scale, n_rep=n_rep)
    return out


def _ring_local_zz_fwd(q, k, v, axis_name, scale, n_rep):
    out, lse, _ = _zz_fwd_local(q, k, v, axis_name=axis_name, scale=scale, n_rep=n_rep)
    return out, (q, k, v, out, lse)


def _ring_local_zz_bwd(axis_name, scale, n_rep, res, g):
    q, k, v, out, lse = res
    return _zz_bwd_local(
        q, k, v, out, lse, g, axis_name=axis_name, scale=scale, n_rep=n_rep
    )


_ring_local_zz.defvjp(_ring_local_zz_fwd, _ring_local_zz_bwd)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _resolve_assignment(assignment: str, causal: bool, sl: int) -> str:
    """zigzag needs causal masking (the balance argument is causal-specific)
    and an even per-rank shard; everything else rides contiguous."""
    if assignment == "auto":
        return "zigzag" if (causal and sl % 2 == 0) else "contiguous"
    if assignment == "zigzag" and not causal:
        raise ValueError("zigzag assignment requires causal=True")
    if assignment == "zigzag" and sl % 2:
        raise ValueError(f"zigzag needs an even per-rank shard, got {sl}")
    if assignment not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring assignment {assignment!r}")
    return assignment


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
    assignment: str = "auto",
) -> jax.Array:
    """Ring attention on LOCAL seq shards, for callers already inside manual
    SPMD (shard_map) over ``axis_name`` — e.g. pipeline stages composing
    with the seq axis.  Inputs/outputs use the CONTIGUOUS layout (rank r
    holds rows [r·sl, (r+1)·sl)); the zigzag layout is internal."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    n_rep = q.shape[1] // k.shape[1]
    assignment = _resolve_assignment(assignment, causal, q.shape[-2])
    if assignment == "zigzag":
        q, k, v = (zigzag_redistribute(t, axis_name) for t in (q, k, v))
        out = _ring_local_zz(q, k, v, axis_name, scale, n_rep)
        return zigzag_redistribute(out, axis_name, inverse=True)
    return _ring_local(q, k, v, axis_name, causal, scale, n_rep)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = MeshAxes.SEQUENCE,
    assignment: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention over global [b, h, S, d] arrays.

    Batch dim may additionally be sharded over data/fsdp axes and heads over
    the tensor axis; the seq dim is sharded over ``seq_axis``.  GQA kv heads
    stay compact around the ring (ppermute traffic is h_kv, not h); the
    gradient re-reduction over the group is explicit in the backward.  Falls
    back to single-shard blockwise attention when the mesh has no seq axis.

    ``assignment``: "auto" (zigzag for causal — balanced per-rank work),
    "contiguous", or "zigzag".
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    n_rep = q.shape[1] // k.shape[1]

    n_seq = mesh.shape.get(seq_axis, 1)
    if n_seq <= 1:
        from determined_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale)

    batch_axes = tuple(
        a for a in MeshAxes.BATCH_AXES if mesh.shape.get(a, 1) > 1
    )
    tensor_size = mesh.shape.get(MeshAxes.TENSOR, 1)
    head_axis = MeshAxes.TENSOR if tensor_size > 1 else None
    if head_axis is not None and k.shape[1] % tensor_size != 0:
        # kv heads can't be sharded over the tensor axis (e.g. MQA with
        # tensor>1): expand to full heads before the ring instead
        k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
        n_rep = 1
    spec = P(batch_axes or None, head_axis, seq_axis, None)
    assignment = _resolve_assignment(assignment, causal, q.shape[-2] // n_seq)

    fn = shard_map(
        lambda q, k, v: ring_attention_local(
            q, k, v, axis_name=seq_axis, causal=causal, scale=scale,
            assignment=assignment,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ring_block_counts(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    seq_axis: str = MeshAxes.SEQUENCE,
    assignment: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Run the forward ring and return (out, per_rank_counts).

    ``per_rank_counts[i]`` is the number of half-block-equivalent computes
    rank i's cond branches actually executed (a full-shard compute counts
    4); this is the balance evidence the zigzag assignment exists for —
    CPU-emulated wall-clock is too noisy to assert on."""
    d = q.shape[-1]
    scale = d ** -0.5
    n_rep = q.shape[1] // k.shape[1]
    n_seq = mesh.shape[seq_axis]
    assignment = _resolve_assignment(assignment, causal, q.shape[-2] // n_seq)
    spec = P(None, None, seq_axis, None)

    def local(q, k, v):
        if assignment == "zigzag":
            q, k, v = (zigzag_redistribute(t, seq_axis) for t in (q, k, v))
            out, _, cnt = _zz_fwd_local(
                q, k, v, axis_name=seq_axis, scale=scale, n_rep=n_rep
            )
            out = zigzag_redistribute(out, seq_axis, inverse=True)
        else:
            out, _, cnt = _ring_fwd_local(
                q, k, v, axis_name=seq_axis, causal=causal, scale=scale,
                n_rep=n_rep,
            )
        return out, cnt[None]

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, P(seq_axis)),
        check_vma=False,
    )
    return fn(q, k, v)
