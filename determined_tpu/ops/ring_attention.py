"""Ring attention: sequence-parallel attention over the mesh "seq" axis.

Long-context machinery the reference platform lacks entirely (SURVEY.md
§2.10: "SP / CP / ring attention ... not present").  Design follows the
blockwise-parallel / ring-attention construction: q, k, v are sharded along
the sequence dim across the "seq" mesh axis; each device computes blockwise
attention of its local queries against the k/v shard it currently holds,
maintaining a running (m, l, acc) softmax state, then passes the k/v shard
to its ring neighbor with ``lax.ppermute`` (XLA lowers this to ICI
neighbor exchanges that overlap with the block compute).

Efficiency notes:
- **Causal step skipping**: a k/v shard that starts strictly after the local
  queries contributes nothing under causal masking; those ring steps skip
  the whole block compute with ``lax.cond`` (the rotation still happens).
  This halves total FLOPs/energy, but with contiguous shard assignment the
  *wall-clock* critical path is still the last rank (which skips nothing);
  converting the saving into time needs zigzag/striped sequence assignment
  so every rank carries a balanced causal workload — future work.
- **Grouped-KV rotation**: with GQA the ring rotates the *kv* heads and
  expands to full heads only inside the local block compute, dividing
  ppermute/ICI traffic by the group size; dk/dv are group-summed back
  before they continue around the ring.  (When the tensor axis does not
  divide h_kv, k/v are pre-expanded instead so head sharding stays legal.)

Memory per device is O(S/N) in BOTH directions: the backward is a custom
VJP that re-runs the ring, rotating (k, v, dk, dv) together so no per-step
k/v residuals are stored (a plain autodiff through the scan would stash
every rotated shard = O(S) per device).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 moved shard_map to jax.shard_map
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from determined_tpu.ops.attention import _repeat_kv
from determined_tpu.parallel.mesh import MeshAxes

NEG_INF = -1e30


def _block_logits(q, k, scale, causal, q_start, k_start, sl):
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        q_pos = q_start + jnp.arange(sl)[:, None]
        k_pos = k_start + jnp.arange(sl)[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return s


def _ring_fwd_local(q, k, v, *, axis_name, causal, scale, n_rep):
    """Forward ring sweep; returns (out, lse) with local seq shards.

    k/v carry ``h_kv`` heads around the ring; expansion to the full head
    count happens per step inside the block compute.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((b, h, sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), jnp.float32)

    def step_fn(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (idx - step) % n

        def compute(m, l, acc):
            k_exp = _repeat_kv(k_cur, n_rep)
            v_exp = _repeat_kv(v_cur, n_rep)
            s = _block_logits(qf, k_exp, scale, causal, idx * sl, src * sl, sl)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p, v_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        if causal:
            # src > idx: the shard lies strictly after every local query —
            # fully masked, skip the block compute entirely
            m, l, acc = jax.lax.cond(
                src <= idx, compute, lambda m, l, acc: (m, l, acc), m, l, acc
            )
        else:
            m, l, acc = compute(m, l, acc)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(step_fn, (m0, l0, acc0, k, v), jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = (acc / l).astype(q.dtype)
    lse = m + jnp.log(l)  # [b, h, sl, 1]
    return out, lse


def _ring_bwd_local(q, k, v, out, lse, do, *, axis_name, causal, scale, n_rep):
    """Backward ring sweep: dk/dv rotate WITH their k/v shards, arriving
    home after n steps; no per-step residuals are kept.  dk/dv travel with
    ``h_kv`` heads (group-summed from the expanded gradient each step)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    h_kv = k.shape[1]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1, keepdims=True)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0 = jnp.zeros((b, h, sl, d), jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)

    def step_fn(carry, step):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (idx - step) % n

        def compute(dq, dk_cur, dv_cur):
            k_exp = _repeat_kv(k_cur, n_rep)
            v_exp = _repeat_kv(v_cur, n_rep)
            s = _block_logits(qf, k_exp, scale, causal, idx * sl, src * sl, sl)
            p = jnp.exp(s - lse)                              # [b,h,ql,kl]
            dp = jnp.einsum(
                "bhqd,bhkd->bhqk", dof, v_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta) * scale
            dq_new = dq + jnp.einsum(
                "bhqk,bhkd->bhqd", ds, k_exp.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            dk_full = jnp.einsum(
                "bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32
            )
            dv_full = jnp.einsum(
                "bhqk,bhqd->bhkd", p, dof, preferred_element_type=jnp.float32
            )
            # group-sum the expanded-head gradient back to kv heads
            dk_new = dk_cur + dk_full.reshape(b, h_kv, n_rep, sl, d).sum(axis=2)
            dv_new = dv_cur + dv_full.reshape(b, h_kv, n_rep, sl, d).sum(axis=2)
            return dq_new, dk_new, dv_new

        if causal:
            dq, dk_cur, dv_cur = jax.lax.cond(
                src <= idx,
                compute,
                lambda dq, dk_cur, dv_cur: (dq, dk_cur, dv_cur),
                dq, dk_cur, dv_cur,
            )
        else:
            dq, dk_cur, dv_cur = compute(dq, dk_cur, dv_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step_fn, (dq0, k, v, dk0, dv0), jnp.arange(n)
    )
    # after n rotations dk/dv have completed a full loop and are home
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_local(q, k, v, axis_name, causal, scale, n_rep):
    out, _ = _ring_fwd_local(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep
    )
    return out


def _ring_local_fwd(q, k, v, axis_name, causal, scale, n_rep):
    out, lse = _ring_fwd_local(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep
    )
    return out, (q, k, v, out, lse)


def _ring_local_bwd(axis_name, causal, scale, n_rep, res, g):
    q, k, v, out, lse = res
    return _ring_bwd_local(
        q, k, v, out, lse, g,
        axis_name=axis_name, causal=causal, scale=scale, n_rep=n_rep,
    )


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = MeshAxes.SEQUENCE,
) -> jax.Array:
    """Sequence-parallel attention over global [b, h, S, d] arrays.

    Batch dim may additionally be sharded over data/fsdp axes and heads over
    the tensor axis; the seq dim is sharded over ``seq_axis``.  GQA kv heads
    stay compact around the ring (ppermute traffic is h_kv, not h); the
    gradient re-reduction over the group is explicit in the backward.  Falls
    back to single-shard blockwise attention when the mesh has no seq axis.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    n_rep = q.shape[1] // k.shape[1]

    if mesh.shape.get(seq_axis, 1) <= 1:
        from determined_tpu.ops.attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale)

    batch_axes = tuple(
        a for a in (MeshAxes.DATA, MeshAxes.FSDP) if mesh.shape.get(a, 1) > 1
    )
    tensor_size = mesh.shape.get(MeshAxes.TENSOR, 1)
    head_axis = MeshAxes.TENSOR if tensor_size > 1 else None
    if head_axis is not None and k.shape[1] % tensor_size != 0:
        # kv heads can't be sharded over the tensor axis (e.g. MQA with
        # tensor>1): expand to full heads before the ring instead
        k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
        n_rep = 1
    spec = P(batch_axes or None, head_axis, seq_axis, None)

    fn = shard_map(
        lambda q, k, v: _ring_local(q, k, v, seq_axis, causal, scale, n_rep),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
