"""TPU compute ops: attention family (reference / Pallas flash / ring)."""

from determined_tpu.ops.attention import dot_product_attention, reference_attention
from determined_tpu.ops.cross_entropy import fused_cross_entropy
from determined_tpu.ops.flash_attention import flash_attention
from determined_tpu.ops.ring_attention import ring_attention

__all__ = [
    "dot_product_attention",
    "reference_attention",
    "flash_attention",
    "fused_cross_entropy",
    "ring_attention",
]
