"""Attention ops: reference implementation + dispatcher.

The reference platform has NO in-repo attention/kernels (SURVEY.md §2.10 —
all math lives in torch/DeepSpeed).  On TPU the attention kernel IS the
performance story, so this framework ships its own:

- ``reference_attention``: pure-jnp softmax attention (correctness anchor,
  small-seq fallback; XLA already fuses it well for short sequences).
- ``flash_attention``: Pallas blockwise kernel (ops/flash_attention.py),
  O(seq) memory, MXU-tiled.
- ``ring_attention``: sequence-parallel blockwise attention over the mesh
  "seq" axis (ops/ring_attention.py) for long-context.

All take [batch, heads, q_len, head_dim] q and [batch, kv_heads, kv_len,
head_dim] k/v (GQA when kv_heads < heads).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand kv heads for grouped-query attention."""
    if n_rep == 1:
        return k
    b, h, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h, n_rep, s, d)).reshape(b, h * n_rep, s, d)


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
) -> jax.Array:
    """Plain softmax attention; the semantics every other impl must match.

    ``q_offset``: global position of q[0] relative to k[0] (used by ring
    attention shards and KV-cache decoding).
    """
    *_, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    n_rep = q.shape[-3] // k.shape[-3]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    scale = scale if scale is not None else head_dim ** -0.5
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q_len)[:, None]
        k_pos = jnp.arange(kv_len)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
    scale: Optional[float] = None,
) -> jax.Array:
    """Dispatcher: 'auto' picks flash on TPU for seqs worth tiling."""
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = "flash" if on_tpu and q.shape[-2] >= 256 else "reference"
    if impl == "reference":
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if impl == "flash":
        from determined_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")
