"""ProfilerContext: system-metrics sampler (reference ``core/_profiler.py``).

Collectors sample host CPU/memory/network/disk plus **TPU device metrics**
(HBM in use / device memory stats via jax, replacing the reference's
pynvml GPU collector) on a daemon thread, reporting into the metrics
shipper under per-resource groups.

Framework-level (XLA) tracing is separate: ``on(trace=True)`` also starts
``jax.profiler`` writing an xplane trace viewable in TensorBoard/XProf —
the analog of the reference's torch.profiler wrapper
(``_pytorch_context.py:426-462``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from determined_tpu.core._distributed import DistributedContext
from determined_tpu.core._metrics import MetricsContext

logger = logging.getLogger("determined_tpu.core.profiler")


def _read_proc_stat() -> Optional[Dict[str, float]]:
    try:
        with open("/proc/stat") as f:
            line = f.readline().split()
        vals = [float(v) for v in line[1:8]]
        idle = vals[3] + vals[4]
        total = sum(vals)
        return {"idle": idle, "total": total}
    except (OSError, ValueError, IndexError):
        return None


def _read_meminfo() -> Dict[str, float]:
    out: Dict[str, float] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                out[k.strip()] = float(rest.split()[0]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return out


def _read_net_bytes() -> Dict[str, float]:
    rx = tx = 0.0
    try:
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                if name.strip() == "lo":
                    continue
                cols = rest.split()
                rx += float(cols[0])
                tx += float(cols[8])
    except (OSError, ValueError, IndexError):
        pass
    return {"rx": rx, "tx": tx}


def _read_disk_bytes() -> Dict[str, float]:
    rd = wr = 0.0
    try:
        with open("/proc/diskstats") as f:
            for line in f:
                cols = line.split()
                if len(cols) < 10:
                    continue
                rd += float(cols[5]) * 512
                wr += float(cols[9]) * 512
    except (OSError, ValueError, IndexError):
        pass
    return {"read": rd, "write": wr}


def _tpu_memory_stats() -> Dict[str, float]:
    out: Dict[str, float] = {}
    try:
        import jax

        for i, d in enumerate(jax.local_devices()):
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            out[f"device{i}_bytes_in_use"] = float(stats.get("bytes_in_use", 0))
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                out[f"device{i}_bytes_limit"] = float(limit)
                out[f"device{i}_hbm_util_pct"] = (
                    100.0 * float(stats.get("bytes_in_use", 0)) / float(limit)
                )
    except Exception:  # noqa: BLE001
        pass
    return out


class ProfilerContext:
    SAMPLE_INTERVAL = 10.0

    def __init__(
        self,
        dist: DistributedContext,
        metrics: MetricsContext,
        trace_dir: Optional[str] = None,
    ) -> None:
        self._dist = dist
        self._metrics = metrics
        self._trace_dir = trace_dir
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._tracing = False
        self._steps_fn = lambda: None  # trainer installs a steps provider

    def set_steps_fn(self, fn) -> None:
        # rebinding a callable attr the sampler reads: a reference store is
        # GIL-atomic; the sampler uses either the old or new provider
        # dtpu: lint-ok[unlocked-shared-state]
        self._steps_fn = fn

    def on(self, sampling: bool = True, trace: bool = False) -> None:
        if sampling and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._sample_loop, daemon=True, name="profiler-sampler"
            )
            self._thread.start()
        if trace and not self._tracing:
            import jax

            trace_dir = self._trace_dir or os.path.join(os.getcwd(), "xplane_traces")
            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            self._tracing = True

    @property
    def tracing(self) -> bool:
        return self._tracing

    def stop_trace(self) -> None:
        """End the xplane capture window (the trainer calls this after
        ``profiling.end_after_batch`` steps — whole-run traces grow
        unboundedly)."""
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            self._report_trace_summary()

    def _report_trace_summary(self) -> None:
        """Parse the just-captured xplane into an op table + category
        totals and report them as a ``profile`` metrics row, so the WebUI
        experiment page renders the profiler surface without launching the
        viewer task (reference: profiler charts on the experiment detail
        page, ``webui/react/src/pages/``).  Chief-only; best-effort — a
        missing xprof toolchain must never fail the trial."""
        if getattr(self._dist, "rank", 0) != 0:
            return
        trace_dir = self._trace_dir or os.path.join(os.getcwd(), "xplane_traces")
        try:
            from determined_tpu.utils import xplane

            ops = xplane.hlo_op_table(trace_dir)
            if not ops:
                return
            totals = xplane.category_totals(ops)
            self._metrics.report(
                "profile",
                self._steps_fn(),
                {
                    # top ops only: the row is a UI artifact, not an archive
                    "op_table": ops[:25],
                    "category_totals": totals,
                },
            )
        except Exception as e:  # noqa: BLE001
            logging.getLogger("determined_tpu.profiler").warning(
                "trace summary not reported: %s", e
            )

    def off(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
        self.stop_trace()

    def _sample_loop(self) -> None:
        prev_cpu = _read_proc_stat()
        prev_net = _read_net_bytes()
        prev_disk = _read_disk_bytes()
        prev_t = time.time()
        while not self._stop.wait(self.SAMPLE_INTERVAL):
            now = time.time()
            dt = max(now - prev_t, 1e-6)
            sample: Dict[str, Any] = {}
            cpu = _read_proc_stat()
            if cpu and prev_cpu:
                didle = cpu["idle"] - prev_cpu["idle"]
                dtotal = cpu["total"] - prev_cpu["total"]
                if dtotal > 0:
                    sample["cpu_util_pct"] = 100.0 * (1.0 - didle / dtotal)
            prev_cpu = cpu
            mem = _read_meminfo()
            if mem.get("MemTotal"):
                sample["memory_used_bytes"] = mem["MemTotal"] - mem.get("MemAvailable", 0.0)
                sample["memory_util_pct"] = 100.0 * sample["memory_used_bytes"] / mem["MemTotal"]
            net = _read_net_bytes()
            sample["net_rx_Bps"] = (net["rx"] - prev_net["rx"]) / dt
            sample["net_tx_Bps"] = (net["tx"] - prev_net["tx"]) / dt
            prev_net = net
            disk = _read_disk_bytes()
            sample["disk_read_Bps"] = (disk["read"] - prev_disk["read"]) / dt
            sample["disk_write_Bps"] = (disk["write"] - prev_disk["write"]) / dt
            prev_disk = disk
            sample.update(_tpu_memory_stats())
            prev_t = now
            try:
                self._metrics.report("system_metrics", self._steps_fn(), sample)
            except RuntimeError:
                return
