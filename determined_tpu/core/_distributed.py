"""Control-plane distributed context.

TPU-native split of responsibilities (reference: ``core/_distributed.py`` +
``ipc.py``):

- **Tensor-plane** collectives (gradient psums, all_gathers) are XLA's job,
  compiled into the jitted step over ICI/DCN.  They never appear here.
- **Control-plane** collectives (checkpoint shard-list merge, preemption
  broadcast, rendezvous of non-tensor facts) are tiny, rare, and
  host-side: a chief-rooted star over TCP sockets (the reference used a
  ZMQ pub-sub + push-pull star, ``ipc.py:34-246``).

One DistributedContext per process.  Rank structure mirrors the
reference (``_distributed.py:16-120``): ``rank``/``size`` are global,
``local_rank``/``local_size`` within a host, ``cross_rank``/``cross_size``
across hosts.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from determined_tpu.utils import faults
from determined_tpu.utils.errors import PeerLostError

logger = logging.getLogger("determined_tpu.core.distributed")

_LEN = struct.Struct(">Q")

# A connection that never sends its hello is half-open (SYN landed, the
# process died, or a port scanner poked us): give it this long, then drop
# it without consuming a worker slot.
HELLO_TIMEOUT = 30.0


def allocate_port(host: str = "127.0.0.1") -> int:
    """Bind-and-release to find a free TCP port (test/rendezvous helper)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


class _StarServer:
    """Chief side of the star: accepts ``n_workers`` identified connections."""

    def __init__(self, port: int, n_workers: int, host: str = "0.0.0.0") -> None:
        self.n_workers = n_workers
        self._conns: Dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self.port = self._listener.getsockname()[1]
        self._listener.listen(max(n_workers, 1))
        if n_workers == 0:
            self._ready.set()
        else:
            self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
            self._accept_thread.start()

    def _accept_loop(self) -> None:
        """Accept until all workers have identified themselves.

        Each accepted connection handshakes on its OWN thread with a hello
        deadline, so one half-open connection (peer died after SYN, or a
        stray scanner) is dropped and logged instead of serially blocking
        every later worker's rendezvous.
        """
        try:
            while not self._ready.is_set():
                conn, addr = self._listener.accept()
                threading.Thread(
                    target=self._handshake, args=(conn, addr), daemon=True
                ).start()
        except OSError:
            return  # listener closed during shutdown

    def _handshake(self, conn: socket.socket, addr: Any) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(HELLO_TIMEOUT)
        try:
            hello = _recv_msg(conn)
            rank = int(hello["rank"])
        except Exception as e:  # noqa: BLE001 - drop, log, keep the slot free
            logger.warning(
                "dropping half-open/garbled connection from %s (no hello within "
                "%.0fs: %s)",
                addr,
                HELLO_TIMEOUT,
                e,
            )
            try:
                conn.close()
            except OSError:
                pass
            return
        conn.settimeout(None)  # collectives set their own deadlines
        with self._lock:
            self._conns[rank] = conn
            done = len(self._conns) >= self.n_workers
        if done:
            self._ready.set()

    def wait_ready(self, timeout: float) -> None:
        if not self._ready.wait(timeout):
            with self._lock:
                have = sorted(self._conns)
            raise TimeoutError(
                f"star rendezvous timed out: {len(have)}/{self.n_workers} workers "
                f"connected (ranks {have})"
            )

    def gather(self, own: Any, timeout: float) -> List[Any]:
        self.wait_ready(timeout)
        out: Dict[int, Any] = {0: own} if 0 not in self._conns else {}
        for rank, conn in self._conns.items():
            # hard deadline: a dead peer must surface as PeerLostError, not
            # hang the gang forever on a blocking recv
            conn.settimeout(timeout)
            try:
                out[rank] = _recv_msg(conn)
            except socket.timeout as e:
                raise PeerLostError(
                    f"gather: rank {rank} sent nothing within {timeout:.0f}s"
                ) from e
            except (ConnectionError, OSError) as e:
                raise PeerLostError(f"gather: rank {rank} connection lost: {e}") from e
        # ranks of workers + chief's own slot; caller supplies ordering map
        return [out[k] for k in sorted(out)]

    def scatter_same(self, value: Any, timeout: float) -> None:
        self.wait_ready(timeout)
        for rank, conn in self._conns.items():
            conn.settimeout(timeout)
            try:
                _send_msg(conn, value)
            except socket.timeout as e:
                raise PeerLostError(
                    f"scatter: rank {rank} not draining within {timeout:.0f}s"
                ) from e
            except (ConnectionError, OSError) as e:
                raise PeerLostError(f"scatter: rank {rank} connection lost: {e}") from e

    def close(self) -> None:
        for c in self._conns.values():
            try:
                c.close()
            except OSError:
                pass
        self._listener.close()


class _StarClient:
    """Worker side: one persistent framed-pickle connection to the chief."""

    def __init__(self, addr: str, port: int, rank: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((addr, port), timeout=timeout)
                break
            except OSError as e:
                last_err = e
                time.sleep(0.1)
        else:
            raise ConnectionError(f"could not reach chief at {addr}:{port}: {last_err}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # create_connection left the connect timeout installed as the socket
        # timeout: every later send/recv inherits the deadline, so a dead
        # chief surfaces as PeerLostError instead of an eternal block
        _send_msg(self._sock, {"rank": rank})

    def send(self, obj: Any) -> None:
        try:
            _send_msg(self._sock, obj)
        except socket.timeout as e:
            raise PeerLostError(f"send to chief timed out: {e}") from e
        except (ConnectionError, OSError) as e:
            raise PeerLostError(f"chief connection lost during send: {e}") from e

    def recv(self) -> Any:
        try:
            return _recv_msg(self._sock)
        except socket.timeout as e:
            raise PeerLostError(f"no reply from chief within deadline: {e}") from e
        except (ConnectionError, OSError) as e:
            raise PeerLostError(f"chief connection lost during recv: {e}") from e

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _Star:
    """A gather/allgather/broadcast group of ``size`` ranks rooted at 0."""

    def __init__(
        self,
        group_rank: int,
        size: int,
        chief_addr: str,
        chief_port: int,
        timeout: float = 600.0,
        bind_host: str = "0.0.0.0",
    ) -> None:
        self.group_rank = group_rank
        self.size = size
        self.timeout = timeout
        self.server: Optional[_StarServer] = None
        self.client: Optional[_StarClient] = None
        self._addr = (chief_addr, chief_port, bind_host)
        # The chief binds eagerly (workers must have something to retry
        # against); workers connect lazily on first collective so ranks
        # that never communicate need no live chief.
        if size > 1 and group_rank == 0:
            self.server = _StarServer(chief_port, size - 1, host=bind_host)

    def _ensure_connected(self) -> None:
        if self.size <= 1 or self.group_rank == 0 or self.client is not None:
            return
        addr, port, _ = self._addr
        self.client = _StarClient(addr, port, self.group_rank, self.timeout)

    def gather(self, obj: Any) -> Optional[List[Any]]:
        faults.fire("distributed.gather", rank=self.group_rank)
        if self.size <= 1:
            return [obj]
        self._ensure_connected()
        if self.server is not None:
            return self.server.gather(obj, self.timeout)
        assert self.client is not None
        self.client.send(obj)
        return None

    def allgather(self, obj: Any) -> List[Any]:
        faults.fire("distributed.allgather", rank=self.group_rank)
        if self.size <= 1:
            return [obj]
        self._ensure_connected()
        if self.server is not None:
            result = self.server.gather(obj, self.timeout)
            self.server.scatter_same(result, self.timeout)
            return result
        assert self.client is not None
        self.client.send(obj)
        return self.client.recv()

    def broadcast(self, obj: Any) -> Any:
        faults.fire("distributed.broadcast", rank=self.group_rank)
        if self.size <= 1:
            return obj
        self._ensure_connected()
        if self.server is not None:
            self.server.scatter_same(obj, self.timeout)
            return obj
        assert self.client is not None
        return self.client.recv()

    def barrier(self) -> None:
        self.allgather(None)

    def close(self) -> None:
        if self.server:
            self.server.close()
        if self.client:
            self.client.close()


class DistributedContext:
    """Rank bookkeeping + control-plane collectives.

    Two stars, like the reference (``_distributed.py:91-168``): a global
    star rooted at rank 0 (the chief) and a per-host star rooted at each
    host's local chief.
    """

    def __init__(
        self,
        *,
        rank: int,
        size: int,
        local_rank: Optional[int] = None,
        local_size: int = 1,
        cross_rank: Optional[int] = None,
        cross_size: Optional[int] = None,
        chief_addr: Optional[str] = None,
        chief_port: Optional[int] = None,
        local_chief_port: Optional[int] = None,
        timeout: float = 600.0,
    ) -> None:
        if size > 1 and (chief_addr is None or chief_port is None):
            raise ValueError("multi-rank DistributedContext requires chief_addr/chief_port")
        # Infer the node topology when not given: one process per node by
        # default (local_size=1), so cross follows from rank/local_size.
        if local_rank is None:
            local_rank = rank % local_size
        if cross_size is None:
            cross_size = size // local_size
        if cross_rank is None:
            cross_rank = rank // local_size
        if local_size * cross_size != size:
            raise ValueError(
                f"local_size ({local_size}) x cross_size ({cross_size}) != size ({size})"
            )
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self._closed = False

        self._global = _Star(rank, size, chief_addr or "127.0.0.1", chief_port or 0, timeout)
        if local_size > 1:
            lport = local_chief_port if local_chief_port is not None else (chief_port or 0) + 1
            self._local = _Star(
                local_rank, local_size, "127.0.0.1", lport, timeout, bind_host="127.0.0.1"
            )
        else:
            self._local = _Star(0, 1, "127.0.0.1", 0, timeout)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_jax(cls, timeout: float = 600.0) -> "DistributedContext":
        """Build from an initialized ``jax.distributed`` runtime plus the
        DTPU_* rendezvous env vars written by the launch layer.

        The timeout doubles as the collective I/O deadline (a silent peer
        past it raises PeerLostError).  Deployments whose checkpoints take
        longer than 10 minutes to restore/upload — workers legitimately
        sit in a barrier that long — raise DTPU_COLLECTIVE_TIMEOUT.
        """
        import jax

        env_timeout = os.environ.get("DTPU_COLLECTIVE_TIMEOUT")
        if env_timeout:
            try:
                timeout = float(env_timeout)
            except ValueError:
                logger.warning("ignoring malformed DTPU_COLLECTIVE_TIMEOUT=%r", env_timeout)
        size = jax.process_count()
        rank = jax.process_index()
        chief_addr = os.environ.get("DTPU_CHIEF_ADDR", "127.0.0.1")
        chief_port = int(os.environ.get("DTPU_CHIEF_PORT", "0") or 0)
        local_size = int(os.environ.get("DTPU_LOCAL_SIZE", "1"))
        local_rank = int(os.environ.get("DTPU_LOCAL_RANK", "0"))
        return cls(
            rank=rank,
            size=size,
            local_rank=local_rank,
            local_size=local_size,
            cross_rank=rank // max(local_size, 1),
            cross_size=max(size // max(local_size, 1), 1),
            chief_addr=chief_addr,
            chief_port=chief_port or None,
            timeout=timeout,
        )

    @classmethod
    def single(cls) -> "DistributedContext":
        return cls(rank=0, size=1)

    # -- predicates --------------------------------------------------------

    def get_rank(self) -> int:
        return self.rank

    def get_size(self) -> int:
        return self.size

    @property
    def is_chief(self) -> bool:
        return self.rank == 0

    @property
    def is_local_chief(self) -> bool:
        return self.local_rank == 0

    # -- collectives -------------------------------------------------------

    def gather(self, obj: Any) -> Optional[List[Any]]:
        """Chief returns [rank0_obj, rank1_obj, ...]; workers return None."""
        return self._global.gather(obj)

    def allgather(self, obj: Any) -> List[Any]:
        return self._global.allgather(obj)

    def broadcast(self, obj: Any) -> Any:
        """Chief's ``obj`` is returned on every rank."""
        return self._global.broadcast(obj)

    def gather_local(self, obj: Any) -> Optional[List[Any]]:
        return self._local.gather(obj)

    def allgather_local(self, obj: Any) -> List[Any]:
        return self._local.allgather(obj)

    def broadcast_local(self, obj: Any = None) -> Any:
        return self._local.broadcast(obj)

    def barrier(self) -> None:
        self._global.barrier()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._global.close()
        self._local.close()

    def __enter__(self) -> "DistributedContext":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DummyDistributedContext(DistributedContext):
    """Single-rank context for off-cluster runs (reference ``_dummy_init``)."""

    def __init__(self) -> None:
        super().__init__(rank=0, size=1)
