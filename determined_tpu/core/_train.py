"""TrainContext: metric/progress/status reporting (reference ``core/_train.py:20-344``)."""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from determined_tpu.core._distributed import DistributedContext
from determined_tpu.core._metrics import MetricsContext

logger = logging.getLogger("determined_tpu.core.train")

TRAINING = "training"
VALIDATION = "validation"


class EarlyExitReason:
    INVALID_HP = "EXITED_REASON_INVALID_HP"
    USER_REQUESTED_STOP = "EXITED_REASON_USER_REQUESTED_STOP"


class TrainContext:
    def __init__(
        self,
        dist: DistributedContext,
        metrics: MetricsContext,
        session: Optional[Any] = None,
        trial_id: Optional[int] = None,
        experiment_id: Optional[int] = None,
    ) -> None:
        self._dist = dist
        self._metrics = metrics
        self._session = session
        self._trial_id = trial_id
        self._experiment_id = experiment_id
        self.searcher_metric_name: Optional[str] = None
        self._last_progress: Optional[float] = None

    # -- reporting ---------------------------------------------------------

    def report_training_metrics(
        self, steps_completed: int, metrics: Dict[str, Any],
        batch_metrics: Optional[list] = None,
    ) -> None:
        body = dict(metrics)
        if batch_metrics is not None:
            body["batch_metrics"] = batch_metrics
        self.report_metrics(TRAINING, steps_completed, body)

    def report_validation_metrics(self, steps_completed: int, metrics: Dict[str, Any]) -> None:
        self.report_metrics(VALIDATION, steps_completed, metrics)

    def report_metrics(self, group: str, steps_completed: int, metrics: Dict[str, Any]) -> None:
        """Arbitrary metric groups, like the reference's generic
        ``report_metrics`` (``_train.py:167``)."""
        if not self._dist.is_chief:
            raise RuntimeError("report_metrics must only be called on the chief")
        self._metrics.report(group, steps_completed, metrics)

    def report_progress(self, progress: float) -> None:
        if not self._dist.is_chief:
            return
        self._last_progress = progress
        if self._session is not None and self._trial_id is not None:
            try:
                # progress is a last-writer-wins scalar: idempotent, opt in
                self._session.post(
                    f"/api/v1/trials/{self._trial_id}/progress",
                    json={"progress": progress},
                    retry=True,
                )
            except Exception:  # noqa: BLE001
                logger.exception("failed to report progress")

    def report_early_exit(self, reason: str) -> None:
        if self._session is not None and self._trial_id is not None:
            try:
                self._session.post(
                    f"/api/v1/trials/{self._trial_id}/early_exit", json={"reason": reason}
                )
            except Exception:  # noqa: BLE001
                logger.exception("failed to report early exit")

    def set_status(self, status: str) -> None:
        if self._session is not None and self._trial_id is not None:
            try:
                self._session.post(
                    f"/api/v1/trials/{self._trial_id}/runner_metadata",
                    json={"state": status},
                )
            except Exception:  # noqa: BLE001
                pass

    def get_experiment_best_validation(self) -> Optional[float]:
        if self._session is None or self._experiment_id is None:
            return None
        try:
            resp = self._session.get(
                f"/api/v1/experiments/{self._experiment_id}/searcher_metric_best"
            )
            return resp.json().get("best")
        except Exception:  # noqa: BLE001
            return None
