"""Heartbeat + log shipping for unmanaged / managed trials.

Reference: ``core/_heartbeat.py`` (liveness POSTs so the master can mark
dead unmanaged runs) and ``core/_log_shipper.py`` (stdout/stderr
interceptor shipping log batches to the task-logs API).
"""

from __future__ import annotations

import io
import logging
import queue
import sys
import threading
import time
from typing import Any, List, Optional

logger = logging.getLogger("determined_tpu.core.heartbeat")


class HeartbeatReporter:
    INTERVAL = 30.0
    # consecutive failures before the master is declared unreachable; a
    # single dropped POST is routine, a streak means a partition/outage
    FAILURE_THRESHOLD = 5

    def __init__(
        self, session: Any, trial_id: int, failure_threshold: Optional[int] = None
    ) -> None:
        self._session = session
        self._trial_id = trial_id
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True, name="heartbeat")
        self._failure_threshold = failure_threshold or self.FAILURE_THRESHOLD
        self._failure_streak = 0
        self._unreachable = threading.Event()

    @property
    def failure_streak(self) -> int:
        """Consecutive heartbeat failures (0 after any success)."""
        return self._failure_streak

    @property
    def master_unreachable(self) -> bool:
        """Latched after ``failure_threshold`` consecutive failures; the
        supervisor / preemption path observes this to make local decisions
        (e.g. checkpoint without waiting on a master ack) instead of
        treating a partition as business as usual.  Cleared when a
        heartbeat lands again."""
        return self._unreachable.is_set()

    def start(self) -> "HeartbeatReporter":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.INTERVAL):
            self._beat()

    def _beat(self) -> bool:
        """One heartbeat attempt; returns success.  Split from the thread
        loop so the failure-streak accounting is directly testable."""
        try:
            self._session.post(f"/api/v1/trials/{self._trial_id}/heartbeat")
        except Exception:  # noqa: BLE001 - counted, not swallowed silently
            # single writer (the reporter thread); the main thread only
            # READS the streak for monitoring, and an int-reference store is
            # GIL-atomic — worst case a read sees the previous streak value
            # dtpu: lint-ok[unlocked-shared-state]
            self._failure_streak += 1
            if self._failure_streak >= self._failure_threshold and not self._unreachable.is_set():
                self._unreachable.set()
                logger.warning(
                    "master unreachable: %d consecutive heartbeat failures "
                    "(threshold %d); latching master_unreachable",
                    self._failure_streak,
                    self._failure_threshold,
                )
            else:
                logger.warning(
                    "heartbeat failed (streak %d/%d)",
                    self._failure_streak,
                    self._failure_threshold,
                    exc_info=True,
                )
            return False
        if self._unreachable.is_set():
            logger.warning(
                "master reachable again after %d missed heartbeats", self._failure_streak
            )
        # same single-writer argument as the failure branch above
        # dtpu: lint-ok[unlocked-shared-state]
        self._failure_streak = 0
        self._unreachable.clear()
        return True

    def close(self) -> None:
        self._stop.set()


class _Interceptor(io.TextIOBase):
    """Tee for a text stream that also enqueues lines for shipping
    (reference ``_log_shipper.py _Interceptor:62``)."""

    def __init__(self, underlying, sink: "queue.Queue[Optional[str]]", stream_name: str) -> None:
        self._underlying = underlying
        self._sink = sink
        self._name = stream_name
        self._buf = ""

    def write(self, s: str) -> int:
        n = self._underlying.write(s)
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            self._sink.put(f"[{self._name}] {line}")
        return n

    def flush(self) -> None:
        self._underlying.flush()

    @property
    def underlying(self):
        return self._underlying


class LogShipper:
    """Intercepts stdout/stderr and ships batched log lines to the master
    task-logs API (or drops them off-cluster)."""

    FLUSH_INTERVAL = 1.0
    MAX_BATCH = 500

    def __init__(self, session: Optional[Any], task_id: Optional[str]) -> None:
        self._session = session
        self._task_id = task_id
        self._queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True, name="log-shipper")
        self._installed = False

    def start(self) -> "LogShipper":
        if self._session is None:
            return self  # nothing to ship to
        sys.stdout = _Interceptor(sys.stdout, self._queue, "stdout")
        sys.stderr = _Interceptor(sys.stderr, self._queue, "stderr")
        self._installed = True
        self._thread.start()
        return self

    def _run(self) -> None:
        done = False
        while not done:
            batch: List[str] = []
            try:
                item = self._queue.get(timeout=self.FLUSH_INTERVAL)
                if item is None:
                    done = True
                else:
                    batch.append(item)
            except queue.Empty:
                pass
            while len(batch) < self.MAX_BATCH:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    done = True
                    break
                batch.append(item)
            if batch and self._session is not None:
                try:
                    self._session.post(
                        "/api/v1/task_logs",
                        json={
                            "task_id": self._task_id,
                            "logs": [
                                {"log": line, "timestamp": time.time()} for line in batch
                            ],
                        },
                    )
                except Exception:  # noqa: BLE001
                    pass

    def close(self) -> None:
        if not self._installed:
            return
        if isinstance(sys.stdout, _Interceptor):
            sys.stdout = sys.stdout.underlying
        if isinstance(sys.stderr, _Interceptor):
            sys.stderr = sys.stderr.underlying
        self._queue.put(None)
        self._thread.join(timeout=10)
        self._installed = False
