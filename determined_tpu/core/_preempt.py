"""PreemptContext: cooperative preemption for trials.

Reference: ``core/_preempt.py:15-313`` — a watcher thread long-polls the
master's preemption-signal endpoint; the chief decides, workers learn the
decision via a control-plane broadcast at batch boundaries; ack on exit.

TPU-native addition: Cloud TPU VMs receive maintenance/preemption as a
**SIGTERM** on the host, so the watcher also latches OS signals — the
analog of the reference's Slurm SIGTERM -> pending_preemption path
(``exec/launch.py:18-55``).
"""

from __future__ import annotations

import enum
import logging
import os
import signal
import threading
import time
from typing import Any, Optional

from determined_tpu.core._distributed import DistributedContext

logger = logging.getLogger("determined_tpu.core.preempt")


class PreemptMode(enum.Enum):
    """Who talks to the master, who syncs with whom
    (reference ``_preempt.py:124-146``)."""

    WorkersAskChief = "workers_ask_chief"
    ChiefOnly = "chief_only"
    WorkersAskMaster = "workers_ask_master"


class _PreemptionWatcher(threading.Thread):
    """Polls the master for the preemption flag (long-poll in the
    reference, ``_preempt.py:54-98``); also latched by signal handler."""

    def __init__(self, session: Any, allocation_id: str, poll_interval: float = 5.0) -> None:
        super().__init__(daemon=True, name="preemption-watcher")
        self._session = session
        self._allocation_id = allocation_id
        self._poll_interval = poll_interval
        self._flag = threading.Event()
        self._stop = threading.Event()

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def latch(self) -> None:
        self._flag.set()

    def run(self) -> None:
        while not self._stop.is_set() and not self._flag.is_set():
            try:
                # read timeout must exceed the server-side long-poll hold
                resp = self._session.get(
                    f"/api/v1/allocations/{self._allocation_id}/signals/preemption",
                    params={"timeout_seconds": 60},
                    timeout=70,
                )
                if resp.json().get("preempt"):
                    self._flag.set()
                    return
            except Exception:  # noqa: BLE001
                logger.debug("preemption poll failed; retrying", exc_info=True)
            self._stop.wait(self._poll_interval)

    def close(self) -> None:
        self._stop.set()


class PreemptContext:
    def __init__(
        self,
        dist: DistributedContext,
        session: Optional[Any] = None,
        allocation_id: Optional[str] = None,
        mode: PreemptMode = PreemptMode.WorkersAskChief,
        register_signal_handler: bool = True,
    ) -> None:
        self._dist = dist
        self._session = session
        self._allocation_id = allocation_id
        self._mode = mode
        self._watcher: Optional[_PreemptionWatcher] = None
        # a plain bool, NOT an Event: it is set from the SIGTERM handler,
        # and Event.set takes the Event's internal Condition lock — if the
        # signal interrupts the main thread inside simulate()'s own set()
        # (serial-mode trials run ON the main thread) the handler would
        # self-deadlock.  A GIL-atomic attribute write has no lock to hold.
        self._local_flag = False
        self._acked = False
        self._started = False
        self._register_signal_handler = register_signal_handler
        self._prev_sigterm: Any = None

    def start(self) -> "PreemptContext":
        if self._started:
            return self
        self._started = True
        watch_master = (
            self._session is not None
            and bool(self._allocation_id)
            and (self._mode == PreemptMode.WorkersAskMaster or self._dist.is_chief)
        )
        if watch_master:
            self._watcher = _PreemptionWatcher(self._session, self._allocation_id or "")
            self._watcher.start()
        if self._register_signal_handler and threading.current_thread() is threading.main_thread():
            self._prev_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, self._on_sigterm)
        return self

    def _on_sigterm(self, signum, frame) -> None:
        # flag-set pattern ONLY: the handler interrupts the main thread at
        # an arbitrary bytecode boundary, so it must not touch the logging
        # module lock or any Event's Condition lock the interrupted frame
        # might hold.  os.write to stderr is the async-signal-tolerable way
        # to stay visible; the watcher latch happens when the flag is next
        # OBSERVED on a normal thread (_flag below).
        self._local_flag = True
        os.write(2, b"determined-tpu: SIGTERM received, latching preemption flag\n")
        if callable(self._prev_sigterm):
            self._prev_sigterm(signum, frame)

    def _flag(self) -> bool:
        if self._local_flag:
            if self._watcher is not None:
                # normal-thread context: stop the long-poll loop early
                self._watcher.latch()
            return True
        return self._watcher.preempted if self._watcher is not None else False

    def should_preempt(self, auto_ack: bool = True) -> bool:
        """Collective at batch boundaries under WorkersAskChief: the chief
        reads the flag and broadcasts so every rank acts in the same step."""
        if not self._started:
            raise RuntimeError("PreemptContext not started")
        if self._mode == PreemptMode.WorkersAskChief:
            # allgather (not chief broadcast) so a SIGTERM delivered to ANY
            # host — TPU maintenance events hit individual hosts — triggers
            # a coordinated checkpoint+exit on every rank.
            out = any(self._dist.allgather(self._flag()))
        elif self._mode == PreemptMode.ChiefOnly:
            if not self._dist.is_chief:
                raise RuntimeError("ChiefOnly mode: only the chief may call should_preempt")
            out = self._flag()
        else:
            out = self._flag()
        if out and auto_ack:
            self.acknowledge_preemption_signal()
        return out

    def simulate(self) -> None:
        """Programmatically trigger preemption (tests / local orchestrator).
        A plain flag write, so the experiment-level signal path may call it
        from a handler without lock-reentrancy hazards."""
        self._local_flag = True

    def acknowledge_preemption_signal(self) -> None:
        """Tell the master we saw the signal and will checkpoint+exit
        (reference ``_preempt.py:257``)."""
        if self._acked or not self._dist.is_chief:
            return
        self._acked = True
        if self._session is not None and self._allocation_id:
            try:
                self._session.post(
                    f"/api/v1/allocations/{self._allocation_id}/signals/ack_preemption"
                )
            except Exception:  # noqa: BLE001
                logger.exception("failed to ack preemption")

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.close()
        if (
            self._register_signal_handler
            and self._prev_sigterm is not None
            and threading.current_thread() is threading.main_thread()
        ):
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, TypeError):
                pass
