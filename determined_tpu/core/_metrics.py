"""Background metric shipper (reference: ``core/_metrics.py:13-206``).

Training code must never block on metric I/O (on TPU a host sync in the
hot loop stalls the device pipeline), so reports are enqueued and a
daemon thread batches them to the sink: the master's metrics API when a
session exists, else a local JSONL file.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("determined_tpu.core.metrics")

SHIP_INTERVAL = 1.0  # seconds between batch flushes
MAX_BATCH = 1000


class MetricsContext:
    def __init__(
        self,
        session: Optional[Any] = None,
        trial_id: Optional[int] = None,
        run_id: int = 0,
        local_path: Optional[str] = None,
    ) -> None:
        self._session = session
        self._trial_id = trial_id
        self._run_id = run_id
        self._local_path = local_path
        self._queue: "queue.Queue[Optional[Dict[str, Any]]]" = queue.Queue()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True, name="metrics-shipper")
        self._started = False

    def start(self) -> "MetricsContext":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def report(
        self,
        group: str,
        steps_completed: Optional[int],
        metrics: Dict[str, Any],
        report_time: Optional[float] = None,
    ) -> None:
        if self._error is not None:
            raise RuntimeError("metrics shipper thread died") from self._error
        self._queue.put(
            {
                "group": group,
                "steps_completed": steps_completed,
                "metrics": metrics,
                "report_time": report_time if report_time is not None else time.time(),
                "trial_id": self._trial_id,
                "trial_run_id": self._run_id,
            }
        )

    def close(self) -> None:
        if not self._started:
            return
        self._queue.put(None)
        self._thread.join(timeout=30)
        self._started = False

    # -- shipper thread ----------------------------------------------------

    def _run(self) -> None:
        try:
            done = False
            while not done:
                batch: List[Dict[str, Any]] = []
                try:
                    item = self._queue.get(timeout=SHIP_INTERVAL)
                    if item is None:
                        done = True
                    else:
                        batch.append(item)
                except queue.Empty:
                    pass
                while len(batch) < MAX_BATCH:
                    try:
                        item = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        done = True
                        break
                    batch.append(item)
                if batch:
                    try:
                        self._ship(batch)
                    except Exception:  # noqa: BLE001
                        # Metric shipping must never kill training: drop the
                        # batch, keep the thread alive for the next one.
                        logger.exception("failed to ship %d metrics; dropped", len(batch))
        except BaseException as e:  # noqa: BLE001
            # terminal single write as the shipper dies; consumers observe
            # it only after noticing the thread is gone (GIL-atomic store)
            # dtpu: lint-ok[unlocked-shared-state]
            self._error = e
            logger.exception("metrics shipper thread failed")

    def _ship(self, batch: List[Dict[str, Any]]) -> None:
        if self._session is not None:
            self._session.post("/api/v1/trials/metrics", json={"metrics": batch})
            return
        if self._local_path is not None:
            os.makedirs(os.path.dirname(self._local_path) or ".", exist_ok=True)
            with open(self._local_path, "a") as f:
                for m in batch:
                    f.write(json.dumps(m, default=_json_default) + "\n")


def _json_default(o: Any) -> Any:
    try:
        import numpy as np

        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return str(o)
