"""CheckpointContext: durable, shardable checkpoint upload/restore.

Reference: ``harness/determined/core/_checkpoint.py:171-778`` — upload /
download / store_path / restore_path / delete against a StorageManager,
with ``shard=True`` meaning every rank contributes files to ONE logical
checkpoint; per-rank file lists and metadata are merged via control-plane
allgather with md5 conflict detection (``merge_resources:127``,
``merge_metadata:84``).

TPU-native notes: jax sharded-array serialization itself lives in
``determined_tpu.train.serialization`` (each process writes its
addressable shards); this context is the transport + merge + registry
layer on top.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import shutil
import tempfile
import uuid as uuid_mod
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from determined_tpu.core._distributed import DistributedContext
from determined_tpu.storage.base import StorageManager, file_md5, list_directory
from determined_tpu.utils.errors import CheckpointCorruptError, ShardMergeConflictError

logger = logging.getLogger("determined_tpu.core.checkpoint")

METADATA_FILE = "metadata.json"
MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1


def build_manifest(
    resources: Dict[str, int],
    digests: Dict[str, str],
    parent: Optional[str] = None,
) -> Dict[str, Any]:
    """Integrity manifest for one checkpoint: per-file sizes + md5 digests.

    Uploaded as the ATOMIC LAST step of finalize, so its presence asserts
    every listed file landed completely — a trial killed mid-upload leaves
    no manifest and the checkpoint is visibly incomplete (the reference
    never resumes from a checkpoint the master hasn't recorded as
    COMPLETED; the manifest is the storage-plane analog of that record).
    ``parent`` names the previous good checkpoint so a verifier that
    rejects this one can fall back.
    """
    files: Dict[str, Any] = {}
    for rel, size in resources.items():
        if rel.endswith("/") or rel == MANIFEST_FILE:
            continue
        entry: Dict[str, Any] = {"size": int(size)}
        if digests.get(rel):
            entry["md5"] = digests[rel]
        files[rel] = entry
    return {"version": MANIFEST_VERSION, "parent": parent, "files": files}


def verify_manifest(path: str, require_manifest: bool = False) -> bool:
    """Check a local checkpoint directory against its manifest.

    Returns True when verified, False when no manifest exists (legacy /
    foreign checkpoint) and ``require_manifest`` is unset.  Raises
    ``CheckpointCorruptError`` on a missing-but-required manifest, an
    unreadable manifest, or any size/digest mismatch — the caller must
    treat the checkpoint as poison and fall back.
    """
    mf = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mf):
        if require_manifest:
            raise CheckpointCorruptError(
                f"checkpoint at {path} has no {MANIFEST_FILE}: finalize never "
                "completed (killed mid-upload?)"
            )
        logger.warning(
            "checkpoint at %s has no %s; skipping integrity verification", path, MANIFEST_FILE
        )
        return False
    try:
        with open(mf) as f:
            manifest = json.load(f)
        files = dict(manifest["files"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise CheckpointCorruptError(f"unreadable manifest at {mf}: {e}") from e
    problems: List[str] = []
    for rel, entry in files.items():
        full = os.path.join(path, rel)
        if not os.path.isfile(full):
            problems.append(f"{rel}: missing")
            continue
        size = os.path.getsize(full)
        if size != entry.get("size"):
            problems.append(f"{rel}: size {size} != manifest {entry.get('size')}")
            continue
        want = entry.get("md5")
        if want and file_md5(full) != want:
            problems.append(f"{rel}: md5 mismatch")
    if problems:
        raise CheckpointCorruptError(
            f"checkpoint at {path} failed manifest verification: {'; '.join(problems)}"
        )
    return True


def merge_resources(
    all_resources: List[Dict[str, int]],
    all_digests: List[Dict[str, str]],
) -> Dict[str, int]:
    """Merge per-rank file lists; duplicate paths must be bit-identical.

    Mirrors reference semantics (``_checkpoint.py merge_resources:127``):
    directories may repeat freely; files may repeat only with equal md5.
    """
    merged: Dict[str, int] = {}
    owner: Dict[str, int] = {}
    digests: Dict[str, str] = {}
    for rank, (resources, rank_digests) in enumerate(zip(all_resources, all_digests)):
        for rel, size in resources.items():
            if rel.endswith("/"):
                merged.setdefault(rel, 0)
                continue
            if rel == METADATA_FILE:
                continue
            if rel in merged:
                if digests.get(rel) != rank_digests.get(rel):
                    raise ShardMergeConflictError(
                        f"file '{rel}' uploaded by ranks {owner[rel]} and {rank} "
                        "with different contents"
                    )
                continue
            merged[rel] = size
            owner[rel] = rank
            digests[rel] = rank_digests.get(rel, "")
    return merged


def _merge_digests(all_digests: List[Dict[str, str]]) -> Dict[str, str]:
    """First-writer-wins union of per-rank digest maps; conflicts were
    already rejected by ``merge_resources``."""
    merged: Dict[str, str] = {}
    for rank_digests in all_digests:
        for rel, digest in rank_digests.items():
            merged.setdefault(rel, digest)
    return merged


def merge_metadata(all_metadata: List[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Key-wise merge; the same key must carry the same value on all ranks
    (reference ``merge_metadata:84``)."""
    merged: Dict[str, Any] = {}
    owner: Dict[str, int] = {}
    for rank, md in enumerate(all_metadata):
        if not md:
            continue
        for k, v in md.items():
            if k in merged and merged[k] != v:
                raise ShardMergeConflictError(
                    f"metadata key '{k}' set to conflicting values by ranks "
                    f"{owner[k]} and {rank}"
                )
            merged.setdefault(k, v)
            owner.setdefault(k, rank)
    return merged


class CheckpointContext:
    def __init__(
        self,
        dist: DistributedContext,
        storage_manager: StorageManager,
        session: Optional[Any] = None,
        trial_id: Optional[int] = None,
        staging_dir: Optional[str] = None,
    ) -> None:
        self._dist = dist
        self._storage = storage_manager
        self._session = session
        self._trial_id = trial_id
        self._staging_dir = staging_dir or tempfile.gettempdir()

    # -- write path --------------------------------------------------------

    def upload(
        self,
        ckpt_dir: Optional[str],
        metadata: Optional[Dict[str, Any]] = None,
        *,
        shard: bool = False,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> str:
        """Upload a directory as one checkpoint; returns its storage id.

        Non-sharded: chief-only call.  Sharded: collective — every rank
        calls (``ckpt_dir=None`` ok for ranks with nothing to add).
        """
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("upload(shard=False) must only be called on the chief")
            if ckpt_dir is None:
                raise ValueError("chief upload requires ckpt_dir")
            storage_id = str(uuid_mod.uuid4())
            paths = self._selected(ckpt_dir, selector)
            selected = set(paths)
            self._storage.upload(ckpt_dir, storage_id, paths=paths)
            resources = {p: sz for p, sz in list_directory(ckpt_dir).items() if p in selected}
            digests = {
                p: file_md5(os.path.join(ckpt_dir, p))
                for p in paths
                if not p.endswith("/") and p != METADATA_FILE
            }
            self._finalize(storage_id, resources, dict(metadata or {}), digests)
            return storage_id
        return self._upload_sharded(ckpt_dir, metadata, selector)

    def _upload_sharded(
        self,
        ckpt_dir: Optional[str],
        metadata: Optional[Dict[str, Any]],
        selector: Optional[Callable[[str], bool]],
    ) -> str:
        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if ckpt_dir is not None:
            paths = self._selected(ckpt_dir, selector)
            selected = set(paths)
            resources = {
                p: sz for p, sz in list_directory(ckpt_dir).items() if p in selected
            }
            digests = {
                p: file_md5(os.path.join(ckpt_dir, p))
                for p in paths
                if not p.endswith("/")
            }
            self._storage.upload(ckpt_dir, storage_id, paths=paths)
        else:
            resources, digests = {}, {}
        gathered = self._dist.gather((resources, digests, dict(metadata or {})))
        if self._dist.is_chief:
            assert gathered is not None
            merged = merge_resources([g[0] for g in gathered], [g[1] for g in gathered])
            merged_md = merge_metadata([g[2] for g in gathered])
            self._finalize(
                storage_id, merged, merged_md, _merge_digests([g[1] for g in gathered])
            )
        self._dist.barrier()
        return storage_id

    def _selected(self, ckpt_dir: str, selector: Optional[Callable[[str], bool]]) -> List[str]:
        names = list(list_directory(ckpt_dir))
        if selector is None:
            return names
        return [n for n in names if n.endswith("/") or selector(n)]

    def _finalize(
        self,
        storage_id: str,
        resources: Dict[str, int],
        metadata: Dict[str, Any],
        digests: Optional[Dict[str, str]] = None,
    ) -> None:
        """Write merged metadata, then the integrity manifest (the ATOMIC
        last step — its presence certifies the whole upload), then report
        to the master."""
        metadata = dict(metadata)
        metadata.setdefault("format", "determined_tpu")
        with tempfile.TemporaryDirectory() as td:
            md_path = os.path.join(td, METADATA_FILE)
            with open(md_path, "w") as f:
                json.dump(metadata, f, indent=2, sort_keys=True)
            self._storage.upload(td, storage_id, paths=[METADATA_FILE])
            # the manifest covers the data files AND the metadata file just
            # written; anything that dies between here and the manifest
            # upload leaves a checkpoint that verification rejects
            full = dict(resources)
            full[METADATA_FILE] = os.path.getsize(md_path)
            all_digests = dict(digests or {})
            all_digests[METADATA_FILE] = file_md5(md_path)
            manifest = build_manifest(
                full, all_digests, parent=metadata.get("parent_storage_id")
            )
            with open(os.path.join(td, MANIFEST_FILE), "w") as f:
                json.dump(manifest, f, indent=2, sort_keys=True)
            self._storage.upload(td, storage_id, paths=[MANIFEST_FILE])
        self._report_checkpoint(storage_id, resources, metadata)

    def _report_checkpoint(
        self, storage_id: str, resources: Dict[str, int], metadata: Dict[str, Any]
    ) -> None:
        """Record the checkpoint with the master (reference
        ``_report_checkpoint:709``); no-op off-cluster."""
        if self._session is None:
            return
        try:
            # keyed by uuid server-side, so a duplicate report is a no-op:
            # safe to opt this POST into transport retries
            self._session.post(
                "/api/v1/checkpoints",
                json={
                    "uuid": storage_id,
                    "trial_id": self._trial_id,
                    "resources": resources,
                    "metadata": metadata,
                },
                retry=True,
            )
        except Exception:  # noqa: BLE001 - reporting must not kill training
            logger.exception("failed to report checkpoint %s to master", storage_id)

    @contextlib.contextmanager
    def store_path(
        self, metadata: Optional[Dict[str, Any]] = None, *, shard: bool = False
    ) -> Iterator[Tuple[str, str]]:
        """Yield (path, storage_id); whatever the caller writes there is the
        checkpoint.  Sharded variant is collective like upload(shard=True)."""
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("store_path(shard=False) must only be called on the chief")
            storage_id = str(uuid_mod.uuid4())
            with self._storage.store_path(storage_id, self._staging_dir) as path:
                yield path, storage_id
                resources, digests = self._list_and_digest(path)
            self._finalize(storage_id, resources, dict(metadata or {}), digests)
            return
        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if self._storage.direct_store:
            # shared fs: every rank writes straight into the one durable dir
            with self._storage.store_path(storage_id, self._staging_dir) as path:
                yield path, storage_id
                # All ranks see the same directory; wait until everyone
                # finished writing before listing/digesting, or one rank may
                # hash another's half-written file.  One reporter per host is
                # enough — the dir holds every rank's files.
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
        else:
            # staged backend (cloud): all local ranks stage into ONE
            # deterministic per-storage_id dir — collective array writers
            # (orbax) require a single directory per host — then only the
            # local chief lists/digests/uploads and cleans up, once per host.
            path = self._storage.stage_path(storage_id, self._staging_dir)
            try:
                yield path, storage_id
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
                if self._dist.is_local_chief:
                    self._storage.upload(path, storage_id)
                # uploads on every host must complete before any rank returns
                self._dist.barrier()
            finally:
                if self._dist.is_local_chief:
                    shutil.rmtree(path, ignore_errors=True)
        self._merge_and_finalize(storage_id, resources, digests, dict(metadata or {}))

    def _merge_and_finalize(self, storage_id, resources, digests, metadata) -> None:
        gathered = self._dist.gather((resources, digests, metadata))
        if self._dist.is_chief:
            assert gathered is not None
            # With a true shared fs all ranks report overlapping dir trees;
            # md5 equality keeps that legal while catching real conflicts.
            merged = merge_resources([g[0] for g in gathered], [g[1] for g in gathered])
            merged_md = merge_metadata([g[2] for g in gathered])
            self._finalize(
                storage_id, merged, merged_md, _merge_digests([g[1] for g in gathered])
            )
        self._dist.barrier()

    def store_path_async(
        self, metadata: Optional[Dict[str, Any]] = None, *, shard: bool = False
    ):
        """Overlapped-checkpointing variant of ``store_path``: returns
        ``(path, storage_id, finish)``.

        The caller may write into ``path`` from a BACKGROUND thread while
        training continues; once the writes are done, ``finish()`` must be
        called from the MAIN thread at a point where every rank reaches it
        in the same loop position (the next save, preemption, or exit) — it
        runs the same collective merge/upload/report as ``store_path``'s
        exit.  Keeping the control-plane collectives on the main thread at
        deterministic points is what makes overlap safe: background threads
        never touch the distributed context, so an in-flight save can never
        interleave with a preemption broadcast.  SURVEY §7(b) names async
        checkpointing as a hard part of the TPU build; the reference blocks
        through serialize+upload (``core/_checkpoint.py`` ``_upload_sharded``).
        """
        metadata = dict(metadata or {})
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("store_path(shard=False) must only be called on the chief")
            storage_id = str(uuid_mod.uuid4())
            cm = self._storage.store_path(storage_id, self._staging_dir)
            path = cm.__enter__()

            def finish() -> None:
                try:
                    resources, digests = self._list_and_digest(path)
                finally:
                    cm.__exit__(None, None, None)
                self._finalize(storage_id, resources, metadata, digests)

            return path, storage_id, finish

        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if self._storage.direct_store:
            cm = self._storage.store_path(storage_id, self._staging_dir)
            path = cm.__enter__()

            def finish() -> None:
                try:
                    self._dist.barrier()
                    resources, digests = (
                        self._list_and_digest(path)
                        if self._dist.is_local_chief
                        else ({}, {})
                    )
                finally:
                    cm.__exit__(None, None, None)
                self._merge_and_finalize(storage_id, resources, digests, metadata)

            return path, storage_id, finish

        path = self._storage.stage_path(storage_id, self._staging_dir)

        def finish() -> None:
            try:
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
                if self._dist.is_local_chief:
                    self._storage.upload(path, storage_id)
                self._dist.barrier()
            finally:
                if self._dist.is_local_chief:
                    shutil.rmtree(path, ignore_errors=True)
            self._merge_and_finalize(storage_id, resources, digests, metadata)

        return path, storage_id, finish

    def _list_and_digest(self, path: str):
        # Called by local chiefs only: every rank on a host shares the
        # directory, so one lister/digester per host avoids local_size×
        # re-hashing of the full checkpoint; cross-host md5 conflict
        # detection is preserved because each host still reports.
        resources = list_directory(path)
        digests = {
            p: file_md5(os.path.join(path, p))
            for p in resources
            if not p.endswith("/") and p != METADATA_FILE
        }
        return resources, digests

    # -- read path ---------------------------------------------------------

    def download(
        self,
        storage_id: str,
        ckpt_dir: str,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> None:
        os.makedirs(ckpt_dir, exist_ok=True)
        self._storage.download(storage_id, ckpt_dir, selector=selector)

    @contextlib.contextmanager
    def restore_path(
        self,
        storage_id: str,
        selector: Optional[Callable[[str], bool]] = None,
        *,
        verify: bool = True,
        require_manifest: bool = False,
    ) -> Iterator[str]:
        """Yield a local path containing the checkpoint.

        Download-once-per-host semantics (reference ``DownloadMode`` /
        ``restore_path:599``): the local chief downloads (or direct-mounts
        for shared_fs), others wait on the local star.

        The local chief verifies the integrity manifest before any rank
        sees the path (skipped for partial ``selector`` restores).  With
        ``require_manifest`` a manifest-less checkpoint — e.g. one whose
        writer was killed mid-upload, before finalize — is rejected as
        corrupt rather than trusted; resume paths set this so a partial
        upload can never poison a resume.
        """
        if self._dist.is_local_chief:
            try:
                cm = self._storage.restore_path(storage_id, self._staging_dir)
                path = cm.__enter__()
            except Exception as e:
                # Unblock local peers with an error sentinel instead of
                # leaving them hanging on the local star until timeout.
                self._dist.broadcast_local(("error", f"{type(e).__name__}: {e}"))
                raise
            if verify and selector is None:
                try:
                    verify_manifest(path, require_manifest=require_manifest)
                except Exception as e:
                    self._dist.broadcast_local(("error", f"{type(e).__name__}: {e}"))
                    cm.__exit__(None, None, None)
                    raise
            try:
                self._dist.broadcast_local(("ok", path))
                try:
                    yield path
                finally:
                    # hold the staging dir until every local rank is done
                    self._dist.allgather_local(None)
            finally:
                cm.__exit__(None, None, None)
        else:
            status, payload = self._dist.broadcast_local(None)
            if status == "error":
                # corruption must surface as the same type on every rank so
                # the fallback walk (Trainer._restore_checkpoint) stays in
                # lockstep across the gang
                if str(payload).startswith(
                    ("CheckpointCorruptError", "CheckpointNotFoundError")
                ):
                    from determined_tpu.utils.errors import CheckpointNotFoundError

                    cls = (
                        CheckpointCorruptError
                        if str(payload).startswith("CheckpointCorruptError")
                        else CheckpointNotFoundError
                    )
                    raise cls(f"local chief failed to restore checkpoint: {payload}")
                raise RuntimeError(f"local chief failed to restore checkpoint: {payload}")
            try:
                yield payload
            finally:
                self._dist.allgather_local(None)

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, int]:
        if not self._dist.is_chief:
            raise RuntimeError("delete must only be called on the chief")
        if globs is not None:
            # a partial delete invalidates the integrity manifest; drop it
            # too so the checkpoint reads as "unverified" rather than
            # "corrupt" (resume paths with require_manifest still reject it)
            globs = list(globs) + [MANIFEST_FILE]
        return self._storage.delete(storage_id, globs)

    def get_metadata(self, storage_id: str) -> Dict[str, Any]:
        return self._fetch_json(storage_id, METADATA_FILE)

    def get_manifest(self, storage_id: str) -> Dict[str, Any]:
        """The integrity manifest alone ({} when absent/unreadable)."""
        return self._fetch_json(storage_id, MANIFEST_FILE)

    def get_checkpoint_parent(self, storage_id: str) -> Optional[str]:
        """Previous good checkpoint in this trial's lineage, for fallback
        after a failed verification.  Manifest first; the metadata copy
        covers a checkpoint killed between its metadata and manifest
        uploads."""
        parent = self.get_manifest(storage_id).get("parent")
        if parent:
            return parent
        return self.get_metadata(storage_id).get("parent_storage_id") or None

    def _fetch_json(self, storage_id: str, name: str) -> Dict[str, Any]:
        with tempfile.TemporaryDirectory() as td:
            try:
                self._storage.download(storage_id, td, selector=lambda p: p == name)
            except Exception:
                return {}
            full = os.path.join(td, name)
            if not os.path.exists(full):
                return {}
            try:
                with open(full) as f:
                    return json.load(f)
            except ValueError:
                return {}


class DummyCheckpointContext(CheckpointContext):
    """Off-cluster variant: local directory storage, no master reporting."""

    def __init__(self, dist: DistributedContext, base_path: str) -> None:
        from determined_tpu.storage.shared_fs import SharedFSStorageManager

        super().__init__(dist, SharedFSStorageManager(base_path), session=None)
