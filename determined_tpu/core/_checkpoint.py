"""CheckpointContext: durable, shardable checkpoint upload/restore.

Reference: ``harness/determined/core/_checkpoint.py:171-778`` — upload /
download / store_path / restore_path / delete against a StorageManager,
with ``shard=True`` meaning every rank contributes files to ONE logical
checkpoint; per-rank file lists and metadata are merged via control-plane
allgather with md5 conflict detection (``merge_resources:127``,
``merge_metadata:84``).

TPU-native notes: jax sharded-array serialization itself lives in
``determined_tpu.train.serialization`` (each process writes its
addressable shards); this context is the transport + merge + registry
layer on top.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import shutil
import tempfile
import uuid as uuid_mod
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from determined_tpu.core._distributed import DistributedContext
from determined_tpu.storage.base import StorageManager, file_md5, list_directory
from determined_tpu.utils.errors import ShardMergeConflictError

logger = logging.getLogger("determined_tpu.core.checkpoint")

METADATA_FILE = "metadata.json"


def merge_resources(
    all_resources: List[Dict[str, int]],
    all_digests: List[Dict[str, str]],
) -> Dict[str, int]:
    """Merge per-rank file lists; duplicate paths must be bit-identical.

    Mirrors reference semantics (``_checkpoint.py merge_resources:127``):
    directories may repeat freely; files may repeat only with equal md5.
    """
    merged: Dict[str, int] = {}
    owner: Dict[str, int] = {}
    digests: Dict[str, str] = {}
    for rank, (resources, rank_digests) in enumerate(zip(all_resources, all_digests)):
        for rel, size in resources.items():
            if rel.endswith("/"):
                merged.setdefault(rel, 0)
                continue
            if rel == METADATA_FILE:
                continue
            if rel in merged:
                if digests.get(rel) != rank_digests.get(rel):
                    raise ShardMergeConflictError(
                        f"file '{rel}' uploaded by ranks {owner[rel]} and {rank} "
                        "with different contents"
                    )
                continue
            merged[rel] = size
            owner[rel] = rank
            digests[rel] = rank_digests.get(rel, "")
    return merged


def merge_metadata(all_metadata: List[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Key-wise merge; the same key must carry the same value on all ranks
    (reference ``merge_metadata:84``)."""
    merged: Dict[str, Any] = {}
    owner: Dict[str, int] = {}
    for rank, md in enumerate(all_metadata):
        if not md:
            continue
        for k, v in md.items():
            if k in merged and merged[k] != v:
                raise ShardMergeConflictError(
                    f"metadata key '{k}' set to conflicting values by ranks "
                    f"{owner[k]} and {rank}"
                )
            merged.setdefault(k, v)
            owner.setdefault(k, rank)
    return merged


class CheckpointContext:
    def __init__(
        self,
        dist: DistributedContext,
        storage_manager: StorageManager,
        session: Optional[Any] = None,
        trial_id: Optional[int] = None,
        staging_dir: Optional[str] = None,
    ) -> None:
        self._dist = dist
        self._storage = storage_manager
        self._session = session
        self._trial_id = trial_id
        self._staging_dir = staging_dir or tempfile.gettempdir()

    # -- write path --------------------------------------------------------

    def upload(
        self,
        ckpt_dir: Optional[str],
        metadata: Optional[Dict[str, Any]] = None,
        *,
        shard: bool = False,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> str:
        """Upload a directory as one checkpoint; returns its storage id.

        Non-sharded: chief-only call.  Sharded: collective — every rank
        calls (``ckpt_dir=None`` ok for ranks with nothing to add).
        """
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("upload(shard=False) must only be called on the chief")
            if ckpt_dir is None:
                raise ValueError("chief upload requires ckpt_dir")
            storage_id = str(uuid_mod.uuid4())
            paths = self._selected(ckpt_dir, selector)
            selected = set(paths)
            self._storage.upload(ckpt_dir, storage_id, paths=paths)
            resources = {p: sz for p, sz in list_directory(ckpt_dir).items() if p in selected}
            self._finalize(storage_id, resources, dict(metadata or {}))
            return storage_id
        return self._upload_sharded(ckpt_dir, metadata, selector)

    def _upload_sharded(
        self,
        ckpt_dir: Optional[str],
        metadata: Optional[Dict[str, Any]],
        selector: Optional[Callable[[str], bool]],
    ) -> str:
        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if ckpt_dir is not None:
            paths = self._selected(ckpt_dir, selector)
            selected = set(paths)
            resources = {
                p: sz for p, sz in list_directory(ckpt_dir).items() if p in selected
            }
            digests = {
                p: file_md5(os.path.join(ckpt_dir, p))
                for p in paths
                if not p.endswith("/")
            }
            self._storage.upload(ckpt_dir, storage_id, paths=paths)
        else:
            resources, digests = {}, {}
        gathered = self._dist.gather((resources, digests, dict(metadata or {})))
        if self._dist.is_chief:
            assert gathered is not None
            merged = merge_resources([g[0] for g in gathered], [g[1] for g in gathered])
            merged_md = merge_metadata([g[2] for g in gathered])
            self._finalize(storage_id, merged, merged_md)
        self._dist.barrier()
        return storage_id

    def _selected(self, ckpt_dir: str, selector: Optional[Callable[[str], bool]]) -> List[str]:
        names = list(list_directory(ckpt_dir))
        if selector is None:
            return names
        return [n for n in names if n.endswith("/") or selector(n)]

    def _finalize(self, storage_id: str, resources: Dict[str, int], metadata: Dict[str, Any]) -> None:
        """Write merged metadata into the checkpoint and report to master."""
        metadata = dict(metadata)
        metadata.setdefault("format", "determined_tpu")
        with tempfile.TemporaryDirectory() as td:
            md_path = os.path.join(td, METADATA_FILE)
            with open(md_path, "w") as f:
                json.dump(metadata, f, indent=2, sort_keys=True)
            self._storage.upload(td, storage_id, paths=[METADATA_FILE])
        self._report_checkpoint(storage_id, resources, metadata)

    def _report_checkpoint(
        self, storage_id: str, resources: Dict[str, int], metadata: Dict[str, Any]
    ) -> None:
        """Record the checkpoint with the master (reference
        ``_report_checkpoint:709``); no-op off-cluster."""
        if self._session is None:
            return
        try:
            self._session.post(
                "/api/v1/checkpoints",
                json={
                    "uuid": storage_id,
                    "trial_id": self._trial_id,
                    "resources": resources,
                    "metadata": metadata,
                },
            )
        except Exception:  # noqa: BLE001 - reporting must not kill training
            logger.exception("failed to report checkpoint %s to master", storage_id)

    @contextlib.contextmanager
    def store_path(
        self, metadata: Optional[Dict[str, Any]] = None, *, shard: bool = False
    ) -> Iterator[Tuple[str, str]]:
        """Yield (path, storage_id); whatever the caller writes there is the
        checkpoint.  Sharded variant is collective like upload(shard=True)."""
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("store_path(shard=False) must only be called on the chief")
            storage_id = str(uuid_mod.uuid4())
            with self._storage.store_path(storage_id, self._staging_dir) as path:
                yield path, storage_id
                resources = list_directory(path)
            self._finalize(storage_id, resources, dict(metadata or {}))
            return
        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if self._storage.direct_store:
            # shared fs: every rank writes straight into the one durable dir
            with self._storage.store_path(storage_id, self._staging_dir) as path:
                yield path, storage_id
                # All ranks see the same directory; wait until everyone
                # finished writing before listing/digesting, or one rank may
                # hash another's half-written file.  One reporter per host is
                # enough — the dir holds every rank's files.
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
        else:
            # staged backend (cloud): all local ranks stage into ONE
            # deterministic per-storage_id dir — collective array writers
            # (orbax) require a single directory per host — then only the
            # local chief lists/digests/uploads and cleans up, once per host.
            path = self._storage.stage_path(storage_id, self._staging_dir)
            try:
                yield path, storage_id
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
                if self._dist.is_local_chief:
                    self._storage.upload(path, storage_id)
                # uploads on every host must complete before any rank returns
                self._dist.barrier()
            finally:
                if self._dist.is_local_chief:
                    shutil.rmtree(path, ignore_errors=True)
        self._merge_and_finalize(storage_id, resources, digests, dict(metadata or {}))

    def _merge_and_finalize(self, storage_id, resources, digests, metadata) -> None:
        gathered = self._dist.gather((resources, digests, metadata))
        if self._dist.is_chief:
            assert gathered is not None
            # With a true shared fs all ranks report overlapping dir trees;
            # md5 equality keeps that legal while catching real conflicts.
            merged = merge_resources([g[0] for g in gathered], [g[1] for g in gathered])
            merged_md = merge_metadata([g[2] for g in gathered])
            self._finalize(storage_id, merged, merged_md)
        self._dist.barrier()

    def store_path_async(
        self, metadata: Optional[Dict[str, Any]] = None, *, shard: bool = False
    ):
        """Overlapped-checkpointing variant of ``store_path``: returns
        ``(path, storage_id, finish)``.

        The caller may write into ``path`` from a BACKGROUND thread while
        training continues; once the writes are done, ``finish()`` must be
        called from the MAIN thread at a point where every rank reaches it
        in the same loop position (the next save, preemption, or exit) — it
        runs the same collective merge/upload/report as ``store_path``'s
        exit.  Keeping the control-plane collectives on the main thread at
        deterministic points is what makes overlap safe: background threads
        never touch the distributed context, so an in-flight save can never
        interleave with a preemption broadcast.  SURVEY §7(b) names async
        checkpointing as a hard part of the TPU build; the reference blocks
        through serialize+upload (``core/_checkpoint.py`` ``_upload_sharded``).
        """
        metadata = dict(metadata or {})
        if not shard:
            if not self._dist.is_chief:
                raise RuntimeError("store_path(shard=False) must only be called on the chief")
            storage_id = str(uuid_mod.uuid4())
            cm = self._storage.store_path(storage_id, self._staging_dir)
            path = cm.__enter__()

            def finish() -> None:
                try:
                    resources = list_directory(path)
                finally:
                    cm.__exit__(None, None, None)
                self._finalize(storage_id, resources, metadata)

            return path, storage_id, finish

        storage_id = self._dist.broadcast(
            str(uuid_mod.uuid4()) if self._dist.is_chief else None
        )
        if self._storage.direct_store:
            cm = self._storage.store_path(storage_id, self._staging_dir)
            path = cm.__enter__()

            def finish() -> None:
                try:
                    self._dist.barrier()
                    resources, digests = (
                        self._list_and_digest(path)
                        if self._dist.is_local_chief
                        else ({}, {})
                    )
                finally:
                    cm.__exit__(None, None, None)
                self._merge_and_finalize(storage_id, resources, digests, metadata)

            return path, storage_id, finish

        path = self._storage.stage_path(storage_id, self._staging_dir)

        def finish() -> None:
            try:
                self._dist.barrier()
                resources, digests = (
                    self._list_and_digest(path)
                    if self._dist.is_local_chief
                    else ({}, {})
                )
                if self._dist.is_local_chief:
                    self._storage.upload(path, storage_id)
                self._dist.barrier()
            finally:
                if self._dist.is_local_chief:
                    shutil.rmtree(path, ignore_errors=True)
            self._merge_and_finalize(storage_id, resources, digests, metadata)

        return path, storage_id, finish

    def _list_and_digest(self, path: str):
        # Called by local chiefs only: every rank on a host shares the
        # directory, so one lister/digester per host avoids local_size×
        # re-hashing of the full checkpoint; cross-host md5 conflict
        # detection is preserved because each host still reports.
        resources = list_directory(path)
        digests = {
            p: file_md5(os.path.join(path, p))
            for p in resources
            if not p.endswith("/") and p != METADATA_FILE
        }
        return resources, digests

    # -- read path ---------------------------------------------------------

    def download(
        self,
        storage_id: str,
        ckpt_dir: str,
        selector: Optional[Callable[[str], bool]] = None,
    ) -> None:
        os.makedirs(ckpt_dir, exist_ok=True)
        self._storage.download(storage_id, ckpt_dir, selector=selector)

    @contextlib.contextmanager
    def restore_path(
        self, storage_id: str, selector: Optional[Callable[[str], bool]] = None
    ) -> Iterator[str]:
        """Yield a local path containing the checkpoint.

        Download-once-per-host semantics (reference ``DownloadMode`` /
        ``restore_path:599``): the local chief downloads (or direct-mounts
        for shared_fs), others wait on the local star.
        """
        if self._dist.is_local_chief:
            try:
                cm = self._storage.restore_path(storage_id, self._staging_dir)
                path = cm.__enter__()
            except Exception as e:
                # Unblock local peers with an error sentinel instead of
                # leaving them hanging on the local star until timeout.
                self._dist.broadcast_local(("error", f"{type(e).__name__}: {e}"))
                raise
            try:
                self._dist.broadcast_local(("ok", path))
                try:
                    yield path
                finally:
                    # hold the staging dir until every local rank is done
                    self._dist.allgather_local(None)
            finally:
                cm.__exit__(None, None, None)
        else:
            status, payload = self._dist.broadcast_local(None)
            if status == "error":
                raise RuntimeError(f"local chief failed to restore checkpoint: {payload}")
            try:
                yield payload
            finally:
                self._dist.allgather_local(None)

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, int]:
        if not self._dist.is_chief:
            raise RuntimeError("delete must only be called on the chief")
        return self._storage.delete(storage_id, globs)

    def get_metadata(self, storage_id: str) -> Dict[str, Any]:
        with tempfile.TemporaryDirectory() as td:
            try:
                self._storage.download(storage_id, td, selector=lambda p: p == METADATA_FILE)
            except Exception:
                return {}
            md = os.path.join(td, METADATA_FILE)
            if not os.path.exists(md):
                return {}
            with open(md) as f:
                return json.load(f)


class DummyCheckpointContext(CheckpointContext):
    """Off-cluster variant: local directory storage, no master reporting."""

    def __init__(self, dist: DistributedContext, base_path: str) -> None:
        from determined_tpu.storage.shared_fs import SharedFSStorageManager

        super().__init__(dist, SharedFSStorageManager(base_path), session=None)
