"""ClusterInfo: facts the launch layer passes into the training process.

Reference: ``harness/determined/_info.py`` (ClusterInfo via DET_* env
vars + rendezvous info file).  Here everything rides DTPU_* env vars,
written by the agent/launch layer before exec'ing the training script.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional


class ClusterInfo:
    def __init__(
        self,
        master_url: Optional[str] = None,
        cluster_id: str = "",
        agent_id: str = "",
        task_id: str = "",
        allocation_id: str = "",
        session_token: str = "",
        trial_id: Optional[int] = None,
        experiment_id: Optional[int] = None,
        trial_run_id: int = 0,
        hparams: Optional[Dict[str, Any]] = None,
        latest_checkpoint: Optional[str] = None,
        trial_seed: int = 0,
        num_slots: int = 1,
        slot_ids: Optional[list] = None,
        rendezvous: Optional[Dict[str, Any]] = None,
        exp_config: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.master_url = master_url
        self.cluster_id = cluster_id
        self.agent_id = agent_id
        self.task_id = task_id
        self.allocation_id = allocation_id
        self.session_token = session_token
        self.trial_id = trial_id
        self.experiment_id = experiment_id
        self.trial_run_id = trial_run_id
        self.hparams = hparams or {}
        self.latest_checkpoint = latest_checkpoint
        self.trial_seed = trial_seed
        self.num_slots = num_slots
        self.slot_ids = slot_ids or []
        self.rendezvous = rendezvous or {}
        self.exp_config = exp_config or {}

    @classmethod
    def from_env(cls) -> Optional["ClusterInfo"]:
        """None when not running under the platform (off-cluster)."""
        if "DTPU_MASTER_URL" not in os.environ and "DTPU_TRIAL_ID" not in os.environ:
            return None

        def j(name: str) -> Optional[Dict[str, Any]]:
            raw = os.environ.get(name)
            return json.loads(raw) if raw else None

        return cls(
            master_url=os.environ.get("DTPU_MASTER_URL"),
            cluster_id=os.environ.get("DTPU_CLUSTER_ID", ""),
            agent_id=os.environ.get("DTPU_AGENT_ID", ""),
            task_id=os.environ.get("DTPU_TASK_ID", ""),
            allocation_id=os.environ.get("DTPU_ALLOCATION_ID", ""),
            session_token=os.environ.get("DTPU_SESSION_TOKEN", ""),
            trial_id=int(os.environ["DTPU_TRIAL_ID"]) if "DTPU_TRIAL_ID" in os.environ else None,
            experiment_id=(
                int(os.environ["DTPU_EXPERIMENT_ID"])
                if "DTPU_EXPERIMENT_ID" in os.environ
                else None
            ),
            trial_run_id=int(os.environ.get("DTPU_TRIAL_RUN_ID", "0")),
            hparams=j("DTPU_HPARAMS"),
            latest_checkpoint=os.environ.get("DTPU_LATEST_CHECKPOINT") or None,
            trial_seed=int(os.environ.get("DTPU_TRIAL_SEED", "0")),
            num_slots=int(os.environ.get("DTPU_NUM_SLOTS", "1")),
            slot_ids=json.loads(os.environ.get("DTPU_SLOT_IDS", "[]")),
            rendezvous=j("DTPU_RENDEZVOUS"),
            exp_config=j("DTPU_EXP_CONFIG"),
        )

    def to_env(self) -> Dict[str, str]:
        """Inverse of from_env, used by the launch layer."""
        env: Dict[str, str] = {}
        if self.master_url:
            env["DTPU_MASTER_URL"] = self.master_url
        for k, v in [
            ("DTPU_CLUSTER_ID", self.cluster_id),
            ("DTPU_AGENT_ID", self.agent_id),
            ("DTPU_TASK_ID", self.task_id),
            ("DTPU_ALLOCATION_ID", self.allocation_id),
            ("DTPU_SESSION_TOKEN", self.session_token),
        ]:
            if v:
                env[k] = v
        if self.trial_id is not None:
            env["DTPU_TRIAL_ID"] = str(self.trial_id)
        if self.experiment_id is not None:
            env["DTPU_EXPERIMENT_ID"] = str(self.experiment_id)
        env["DTPU_TRIAL_RUN_ID"] = str(self.trial_run_id)
        if self.hparams:
            env["DTPU_HPARAMS"] = json.dumps(self.hparams)
        if self.latest_checkpoint:
            env["DTPU_LATEST_CHECKPOINT"] = self.latest_checkpoint
        env["DTPU_TRIAL_SEED"] = str(self.trial_seed)
        env["DTPU_NUM_SLOTS"] = str(self.num_slots)
        if self.slot_ids:
            env["DTPU_SLOT_IDS"] = json.dumps(self.slot_ids)
        if self.rendezvous:
            env["DTPU_RENDEZVOUS"] = json.dumps(self.rendezvous)
        if self.exp_config:
            env["DTPU_EXP_CONFIG"] = json.dumps(self.exp_config)
        return env


_info_cache: Optional[ClusterInfo] = None
_info_loaded = False


def get_cluster_info() -> Optional[ClusterInfo]:
    global _info_cache, _info_loaded
    if not _info_loaded:
        _info_cache = ClusterInfo.from_env()
        _info_loaded = True
    return _info_cache


def _reset_cluster_info_cache() -> None:
    global _info_cache, _info_loaded
    _info_cache = None
    _info_loaded = False
