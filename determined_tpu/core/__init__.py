from determined_tpu.core._cluster_info import ClusterInfo, get_cluster_info  # noqa: F401
from determined_tpu.core._distributed import (  # noqa: F401
    DistributedContext,
    DummyDistributedContext,
    allocate_port,
)
from determined_tpu.core._checkpoint import (  # noqa: F401
    MANIFEST_FILE,
    METADATA_FILE,
    CheckpointContext,
    DummyCheckpointContext,
    build_manifest,
    merge_metadata,
    merge_resources,
    verify_manifest,
)
from determined_tpu.core._metrics import MetricsContext  # noqa: F401
from determined_tpu.core._train import TrainContext, EarlyExitReason  # noqa: F401
from determined_tpu.core._preempt import PreemptContext, PreemptMode  # noqa: F401
from determined_tpu.core._profiler import ProfilerContext  # noqa: F401
from determined_tpu.core._heartbeat import HeartbeatReporter, LogShipper  # noqa: F401
from determined_tpu.core._context import Context, init, _dummy_init  # noqa: F401
