"""core.Context: the composed in-training client of the platform.

Reference: ``harness/determined/core/_context.py:231-398`` (``init``) and
``:188-224`` (``_dummy_init``).  The same dummy/real split is preserved:
``init()`` returns a fully functional Context whether or not a master
exists, so any trial runs unchanged on a laptop, a single TPU VM, or a
scheduled multi-host allocation.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Any, Dict, Optional

from determined_tpu.core._checkpoint import CheckpointContext, DummyCheckpointContext
from determined_tpu.core._cluster_info import ClusterInfo, get_cluster_info
from determined_tpu.core._distributed import DistributedContext, DummyDistributedContext
from determined_tpu.core._heartbeat import HeartbeatReporter, LogShipper
from determined_tpu.core._metrics import MetricsContext
from determined_tpu.core._preempt import PreemptContext, PreemptMode
from determined_tpu.core._profiler import ProfilerContext
from determined_tpu.core._train import TrainContext
from determined_tpu.storage.base import StorageManager, from_expconf, from_string

logger = logging.getLogger("determined_tpu.core")


class Context:
    """Composed handle: ``.distributed``, ``.checkpoint``, ``.train``,
    ``.preempt``, ``.profiler``, ``.info``."""

    def __init__(
        self,
        distributed: DistributedContext,
        checkpoint: CheckpointContext,
        train: TrainContext,
        preempt: PreemptContext,
        profiler: ProfilerContext,
        metrics: MetricsContext,
        info: Optional[ClusterInfo] = None,
        session: Optional[Any] = None,
        heartbeat: Optional[HeartbeatReporter] = None,
        log_shipper: Optional[LogShipper] = None,
    ) -> None:
        self.distributed = distributed
        self.checkpoint = checkpoint
        self.train = train
        self.preempt = preempt
        self.profiler = profiler
        self._metrics = metrics
        self.info = info
        self._session = session
        self._heartbeat = heartbeat
        self._log_shipper = log_shipper

    @property
    def metrics(self) -> MetricsContext:
        """Public handle for out-of-band reporters (e.g. the trial
        supervisor's restart counts)."""
        return self._metrics

    @property
    def master_unreachable(self) -> bool:
        """True while the heartbeat reporter has latched a failure streak
        (``_heartbeat.py``); False off-cluster.  The trial supervisor and
        preemption path consult this to act locally during a partition."""
        return bool(self._heartbeat is not None and self._heartbeat.master_unreachable)

    def alert(
        self,
        title: Optional[str] = None,
        description: Optional[str] = None,
        level: str = "info",
    ) -> None:
        """Post a custom webhook event (reference ``_context.py:86-115``)."""
        if self._session is None:
            logger.log(
                logging.getLevelName(level.upper()) if isinstance(level, str) else logging.INFO,
                "ALERT: %s — %s",
                title,
                description,
            )
            return
        try:
            self._session.post(
                "/api/v1/webhooks/custom",
                json={"title": title, "description": description, "level": level},
            )
        except Exception:  # noqa: BLE001
            logger.exception("failed to post alert")

    def start(self) -> "Context":
        self._metrics.start()
        self.preempt.start()
        if self._heartbeat:
            self._heartbeat.start()
        if self._log_shipper:
            self._log_shipper.start()
        return self

    def close(self) -> None:
        self.profiler.off()
        self.preempt.close()
        self._metrics.close()
        if self._heartbeat:
            self._heartbeat.close()
        if self._log_shipper:
            self._log_shipper.close()
        self.distributed.close()

    def __enter__(self) -> "Context":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def init(
    *,
    distributed: Optional[DistributedContext] = None,
    storage_manager: Optional[StorageManager] = None,
    checkpoint_storage: Optional[str] = None,
    preempt_mode: PreemptMode = PreemptMode.WorkersAskChief,
    session: Optional[Any] = None,
    metrics_path: Optional[str] = None,
    info: Optional[Any] = None,
) -> Context:
    """Build a Context from cluster info when present, dummies otherwise.

    ``info`` overrides the env-derived ClusterInfo (used by core_v2
    unmanaged mode, which registers the experiment itself)."""
    info = info or get_cluster_info()

    if session is None and info is not None and info.master_url:
        from determined_tpu.api.session import Session

        session = Session(info.master_url, token=info.session_token or None)

    if distributed is None:
        if info is not None and info.rendezvous:
            distributed = DistributedContext.from_jax()
        else:
            distributed = DummyDistributedContext()

    if storage_manager is None:
        url = checkpoint_storage
        if url is None and info is not None:
            url = (info.exp_config or {}).get("checkpoint_storage")
        if url is None:
            url = os.path.join(os.getcwd(), "checkpoints")
        if isinstance(url, dict):
            # expconf dict form ({"type": "shared_fs", "host_path": ...})
            storage_manager = from_expconf(url)
        else:
            storage_manager = from_string(url) if isinstance(url, str) else url

    checkpoint = CheckpointContext(
        distributed,
        storage_manager,
        session=session,
        trial_id=info.trial_id if info else None,
        staging_dir=tempfile.mkdtemp(prefix="dtpu-ckpt-"),
    )
    metrics = MetricsContext(
        session=session,
        trial_id=info.trial_id if info else None,
        run_id=info.trial_run_id if info else 0,
        local_path=metrics_path
        or (None if session else os.path.join(os.getcwd(), "metrics.jsonl")),
    )
    train = TrainContext(
        distributed,
        metrics,
        session=session,
        trial_id=info.trial_id if info else None,
        experiment_id=info.experiment_id if info else None,
    )
    preempt = PreemptContext(
        distributed,
        session=session,
        allocation_id=info.allocation_id if info else None,
        mode=preempt_mode,
    )
    # xplane traces land in shared checkpoint storage when it has a local
    # path, so a tensorboard/viewer task on any host can serve them
    # (reference: tensorboard task fetching trial event files)
    trace_dir = None
    if hasattr(storage_manager, "base_path") and info is not None and info.trial_id:
        trace_dir = os.path.join(
            storage_manager.base_path, "traces", f"trial_{info.trial_id}"
        )
    profiler = ProfilerContext(distributed, metrics, trace_dir=trace_dir)
    hb_threshold = None
    if info is not None:
        hb_threshold = (
            ((info.exp_config or {}).get("fault_tolerance") or {}).get(
                "heartbeat_failure_threshold"
            )
        )
    heartbeat = (
        HeartbeatReporter(session, info.trial_id, failure_threshold=hb_threshold)
        if session is not None and info is not None and info.trial_id is not None
        else None
    )
    log_shipper = (
        LogShipper(session, info.task_id)
        if session is not None and info is not None and info.task_id
        else None
    )
    ctx = Context(
        distributed=distributed,
        checkpoint=checkpoint,
        train=train,
        preempt=preempt,
        profiler=profiler,
        metrics=metrics,
        info=info,
        session=session,
        heartbeat=heartbeat,
        log_shipper=log_shipper,
    )
    return ctx.start()


def _dummy_init(
    *,
    distributed: Optional[DistributedContext] = None,
    checkpoint_dir: Optional[str] = None,
    metrics_path: Optional[str] = None,
) -> Context:
    """Fully local Context with zero services (reference ``_dummy_init``)."""
    distributed = distributed or DummyDistributedContext()
    checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(prefix="dtpu-dummy-ckpt-")
    checkpoint = DummyCheckpointContext(distributed, checkpoint_dir)
    metrics = MetricsContext(local_path=metrics_path)
    train = TrainContext(distributed, metrics)
    preempt = PreemptContext(distributed, register_signal_handler=False)
    profiler = ProfilerContext(distributed, metrics)
    ctx = Context(
        distributed=distributed,
        checkpoint=checkpoint,
        train=train,
        preempt=preempt,
        profiler=profiler,
        metrics=metrics,
    )
    return ctx.start()
