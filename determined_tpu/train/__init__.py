"""Training engine: JaxTrial + Trainer boundary loop + serialization."""

from determined_tpu.train import _flax_compat

_flax_compat.install()

from determined_tpu.train._jit_cache import (
    clear_step_cache,
    get_step_cache,
    step_cache_stats,
)
from determined_tpu.train._load import load_trial_from_checkpoint
from determined_tpu.train._reducer import MetricReducer, get_reducer
from determined_tpu.train._restart import Attempt, RestartPolicy, run_with_restarts
from determined_tpu.train._state import TrainState
from determined_tpu.train._trainer import Trainer, init
from determined_tpu.train._trial import Callback, JaxTrial, TrialContext
from determined_tpu.train import serialization

__all__ = [
    "Attempt",
    "Callback",
    "JaxTrial",
    "MetricReducer",
    "RestartPolicy",
    "TrainState",
    "Trainer",
    "TrialContext",
    "clear_step_cache",
    "get_reducer",
    "get_step_cache",
    "init",
    "load_trial_from_checkpoint",
    "step_cache_stats",
    "run_with_restarts",
    "serialization",
]
