"""Training engine: JaxTrial + Trainer boundary loop + serialization."""

from determined_tpu.train._state import TrainState
from determined_tpu.train._trainer import Trainer, init
from determined_tpu.train._trial import Callback, JaxTrial, TrialContext
from determined_tpu.train import serialization

__all__ = [
    "Callback",
    "JaxTrial",
    "TrainState",
    "Trainer",
    "TrialContext",
    "init",
    "serialization",
]
