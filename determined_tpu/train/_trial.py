"""JaxTrial: the user-facing trial ABC + TrialContext.

Reference: ``PyTorchTrial`` (``harness/determined/pytorch/_pytorch_trial.py:
1192-1449``) — users subclass, implement data/model/optimizer builders and a
per-batch loss; the framework owns the loop, distribution, checkpointing.

TPU-first divergences:
- ``loss``/``evaluate_batch`` are **pure functions** traced once by XLA; no
  imperative ``backward()``/``step_optimizer()`` calls (reference
  ``_pytorch_context.py:708,814``) — the Trainer differentiates and applies
  updates inside one jitted step.
- parallelism comes from the context's mesh + logical sharding rules, not
  from wrapping (no ``wrap_model``/``wrap_optimizer``).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
import optax
from jax.sharding import Mesh

from determined_tpu.core._context import Context as CoreContext
from determined_tpu.data._loader import DataLoader
from determined_tpu.parallel.mesh import MeshAxes
from determined_tpu.parallel.sharding import DEFAULT_RULES, LogicalAxisRules

Metrics = Dict[str, jax.Array]


class TrialContext:
    """Per-trial handle: hyperparameters + mesh + core services.

    The analog of ``PyTorchTrialContext`` minus all the wrapping methods —
    on TPU the mesh IS the distribution strategy.
    """

    def __init__(
        self,
        core: CoreContext,
        mesh: Mesh,
        hparams: Optional[Dict[str, Any]] = None,
        rules: Optional[LogicalAxisRules] = None,
        seed: int = 0,
        exp_config: Optional[Any] = None,
    ) -> None:
        self.core = core
        self.mesh = mesh
        self.hparams = dict(hparams or {})
        self.rules = dict(rules if rules is not None else DEFAULT_RULES)
        self.seed = seed
        self.exp_config = exp_config

    # -- hyperparameters ---------------------------------------------------

    def get_hparam(self, name: str, default: Any = ...) -> Any:
        if name in self.hparams:
            v = self.hparams[name]
            # collapsed Const from the config system
            return getattr(v, "val", v)
        if default is ...:
            raise KeyError(f"hyperparameter {name!r} not set and no default given")
        return default

    # -- topology ----------------------------------------------------------

    @property
    def distributed(self):
        return self.core.distributed

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def batch_axis_size(self) -> int:
        """Product of batch-carrying mesh axes (dp * fsdp)."""
        n = 1
        for a in MeshAxes.BATCH_AXES:
            n *= self.mesh.shape.get(a, 1)
        return n

    def get_global_batch_size(self) -> int:
        return int(self.get_hparam("global_batch_size", 32))

    def get_per_slot_batch_size(self) -> int:
        gbs = self.get_global_batch_size()
        if gbs % self.batch_axis_size:
            raise ValueError(
                f"global_batch_size {gbs} not divisible by batch mesh axes "
                f"({self.batch_axis_size})"
            )
        return gbs // self.batch_axis_size


class Callback:
    """Training lifecycle hooks — reference ``PyTorchCallback``
    (``harness/determined/pytorch/_callback.py``).  All hooks are host-side
    and run at boundaries, never inside the jitted step."""

    def on_training_start(self, trainer: Any) -> None: ...

    def on_epoch_start(self, epoch: int) -> None: ...

    def on_epoch_end(self, epoch: int) -> None: ...

    def on_validation_start(self) -> None: ...

    def on_validation_end(self, metrics: Dict[str, float]) -> None: ...

    def on_checkpoint_write_start(self, path: str) -> None: ...

    def on_checkpoint_write_end(self, storage_id: str) -> None: ...

    def on_checkpoint_load(self, path: str) -> None: ...

    def on_training_workload_end(
        self, steps_completed: int, metrics: Dict[str, float]
    ) -> None: ...

    def on_trial_shutdown(self) -> None: ...

    # extra state carried through checkpoints
    def state_dict(self) -> Dict[str, Any]:
        return {}

    def load_state_dict(self, state: Dict[str, Any]) -> None: ...


class JaxTrial(abc.ABC):
    """Subclass this; the Trainer drives everything else."""

    def __init__(self, context: TrialContext) -> None:
        self.context = context

    # -- builders ----------------------------------------------------------

    @abc.abstractmethod
    def build_model(self) -> Any:
        """A flax Module (or any object passed through to loss/evaluate)."""

    @abc.abstractmethod
    def build_optimizer(self) -> optax.GradientTransformation:
        ...

    @abc.abstractmethod
    def build_training_data_loader(self) -> DataLoader:
        ...

    @abc.abstractmethod
    def build_validation_data_loader(self) -> DataLoader:
        ...

    def build_callbacks(self) -> Dict[str, Callback]:
        return {}

    # -- pure compute (traced under jit over the mesh) ---------------------

    @abc.abstractmethod
    def loss(
        self,
        model: Any,
        params: Any,
        batch: Dict[str, jax.Array],
        rng: jax.Array,
    ) -> Tuple[jax.Array, Metrics]:
        """Scalar loss + auxiliary metric dict for one training batch."""

    def evaluate_batch(
        self, model: Any, params: Any, batch: Dict[str, jax.Array]
    ) -> Metrics:
        """Validation metrics for one batch; defaults to eval-mode loss."""
        loss, metrics = self.loss(model, params, batch, jax.random.key(0))
        return {"validation_loss": loss, **{f"val_{k}": v for k, v in metrics.items()}}

    def evaluation_reducers(self) -> Dict[str, Any]:
        """Per-metric across-batch reducers (reference
        ``evaluation_reducer``, ``pytorch/_reducer.py``).  Keys are metric
        names from ``evaluate_batch``; values are builtin names
        ("mean"/"sum"/"min"/"max"/"last") or ``train.MetricReducer``
        instances.  Unlisted metrics reduce by mean."""
        return {}

    # -- initialization ----------------------------------------------------

    def init_params(self, model: Any, rng: jax.Array, sample_batch: Dict[str, Any]) -> Any:
        """Build the (unsharded, possibly abstract) parameter pytree.

        Default: flax ``model.init`` on the model's input columns.  Override
        for non-flax models or custom signatures.
        """
        inputs = self.model_inputs(sample_batch)
        return model.init(rng, *inputs)

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        """Which batch columns feed ``model.init``; default: column 'x' or
        the first column."""
        if "x" in batch:
            return (batch["x"],)
        return (next(iter(batch.values())),)

    def restructure_params(self, params: Any) -> Any:
        """Value-preserving post-init restructure of the raw param tree
        (e.g. restacking per-layer blocks into pipeline stages — see
        ``models/transformer.py`` ``split_pipeline_params``).

        Runs under jit right after ``init_params``.  It is a SEPARATE hook
        (rather than part of ``init_params``) so the Trainer can stage the
        two on affected jax versions: a jitted restack into sharded
        out_shardings over a multi-axis mesh SUMS its replicated operands
        there, so the RNG-bearing init materializes replicated and only
        this RNG-free restructure is resharded — see
        ``parallel/_compat.py`` ``sharded_restack_safe``.  Default:
        identity.
        """
        return params

    def compile_cache_runtime_hparams(self) -> Tuple[str, ...]:
        """Hyperparameters that do NOT shape the compiled step.

        The cross-trial jit-reuse cache (``train/_jit_cache.py``) keys the
        shared train/eval steps on every hyperparameter by default, because
        a Python scalar closed over by ``loss``/``build_optimizer`` bakes
        into the HLO as a constant.  A trial that routes an hparam through
        runtime state instead — e.g. a learning rate via
        ``optax.inject_hyperparams`` (it then lives in ``opt_state`` and is
        read by the traced step at run time) — can name it here so trials
        differing only in that hparam share one compiled step.  Naming an
        hparam that actually IS baked into the trace silently reuses the
        first trial's value; only declare hparams you know are runtime.
        """
        return ()

    def param_logical_specs(self, params: Any) -> Optional[Any]:
        """Logical sharding spec pytree for params; None -> infer.

        Inference order: flax ``nn.Partitioned`` metadata if the model
        annotates with ``with_partitioning``; otherwise automatic FSDP
        (largest divisible dim) when the mesh has an fsdp axis.
        """
        return None

    def pipeline_schedule_spec(self) -> Optional[Any]:
        """The trial's pipeline microbatch schedule, as a
        ``parallel/pipeline.py`` ``PipelineSchedule`` — or None when the
        trial does not pipeline (no pipe mesh axis, or a model that does
        not ride ``pipeline_apply``).

        A trial that pipelines should return the schedule it actually
        traces: the Trainer folds it into the jit-reuse cache key (the
        schedule and virtual-stage count reshape the traced program, so
        toggling must never serve a stale trace) and into the goodput
        ledger's ``step.bubble`` rows via the schedule's analytic tick
        model.  Default: no pipeline.
        """
        return None
