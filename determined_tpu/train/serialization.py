"""Sharded-jax-array checkpoint serialization (orbax-backed).

The reference's sharded checkpoint (``core/_checkpoint.py _upload_sharded``)
has every rank write its own files and merges the file lists.  The TPU
analog: every *process* writes only its addressable shards of each global
``jax.Array``; orbax (ocdbt/zarr) is the battle-tested writer for that, so
the array plane rides orbax while loop/loader state rides a plain JSON —
both into the SAME checkpoint directory managed by CheckpointContext.

Layout inside one checkpoint dir:
    state/         orbax pytree (params, opt_state, rng, step)
    trainer_state.json   loop counters, loader state, callbacks state
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

ARRAY_SUBDIR = "state"
TRAINER_STATE_FILE = "trainer_state.json"


def _is_key_dtype(dtype: Any) -> bool:
    import jax.numpy as jnp

    return jnp.issubdtype(dtype, jax.dtypes.prng_key)


def _unkey(tree: Any) -> Any:
    """Replace PRNG-key leaves with their uint32 key data.  Orbax's array
    serializer cannot np.array() extended-dtype key arrays, so keys ride
    as raw counter words and are re-wrapped on restore."""

    def one(x):
        if isinstance(x, jax.Array) and _is_key_dtype(x.dtype):
            return jax.random.key_data(x)
        return x

    return jax.tree.map(one, tree)


def _unkey_abstract(abstract_tree: Any) -> Any:
    """The data-plane aval tree matching ``_unkey``'s output: key leaves
    become their key-data ShapeDtypeStructs (same sharding; trailing
    counter dims are unconstrained by a PartitionSpec prefix)."""

    def one(a):
        if _is_key_dtype(getattr(a, "dtype", None)):
            data = jax.eval_shape(jax.random.key_data, jax.ShapeDtypeStruct(a.shape, a.dtype))
            return jax.ShapeDtypeStruct(
                data.shape, data.dtype, sharding=getattr(a, "sharding", None)
            )
        return a

    return jax.tree.map(one, abstract_tree)


def _rekey(restored: Any, abstract_tree: Any) -> Any:
    """Re-wrap restored key-data leaves into key arrays of the impl the
    abstract tree's dtype carries."""

    def one(x, a):
        if _is_key_dtype(getattr(a, "dtype", None)):
            return jax.random.wrap_key_data(x, impl=a.dtype._impl)
        return x

    return jax.tree.map(one, restored, abstract_tree)


def save_arrays(ckpt_dir: str, tree: Any) -> None:
    """Write a pytree of (possibly sharded) jax arrays; collective across
    processes — every process must call with the same tree structure."""
    path = os.path.join(os.path.abspath(ckpt_dir), ARRAY_SUBDIR)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, _unkey(tree))
        ckptr.wait_until_finished()


def restore_arrays(ckpt_dir: str, abstract_tree: Any) -> Any:
    """Restore into the shardings carried by ``abstract_tree`` (a pytree of
    jax.ShapeDtypeStruct with .sharding set, e.g. from eval_shape +
    shardings)."""
    path = os.path.join(os.path.abspath(ckpt_dir), ARRAY_SUBDIR)
    with ocp.StandardCheckpointer() as ckptr:
        restored = _rekey(ckptr.restore(path, _unkey_abstract(abstract_tree)), abstract_tree)
    # Belt-and-braces: guarantee placement matches the requested shardings
    # (a replicated scalar must span the mesh, not sit on one device, or the
    # next jitted step sees incompatible device sets).  No-op when already
    # placed correctly.
    return jax.tree.map(
        lambda x, a: jax.device_put(x, a.sharding) if getattr(a, "sharding", None) else x,
        restored,
        abstract_tree,
    )


def abstract_like(tree: Any, shardings: Optional[Any] = None) -> Any:
    """ShapeDtypeStruct pytree of ``tree``; shardings taken from the arrays
    themselves unless an explicit sharding pytree is given."""

    def one(x, s=None):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s or x.sharding)
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype, sharding=s)

    if shardings is None:
        return jax.tree.map(one, tree)
    return jax.tree.map(one, tree, shardings)


def save_trainer_state(ckpt_dir: str, state: Dict[str, Any]) -> None:
    with open(os.path.join(ckpt_dir, TRAINER_STATE_FILE), "w") as f:
        json.dump(state, f, indent=2, sort_keys=True)


def load_trainer_state(ckpt_dir: str) -> Dict[str, Any]:
    with open(os.path.join(ckpt_dir, TRAINER_STATE_FILE)) as f:
        return json.load(f)
