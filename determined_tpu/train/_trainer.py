"""Trainer: the boundary-driven training loop engine.

Reference: ``_PyTorchTrialController`` (``harness/determined/pytorch/
_pytorch_trial.py:398-1088``) + ``Trainer``/``init`` (``_trainer.py:18-386``).
Same contract — fit(max_length, periods, latest_checkpoint) with
TRAIN/VALIDATE/CHECKPOINT/REPORT boundaries, preemption-safe, resumable —
redesigned for XLA:

- ONE jitted train step (forward+backward+update+metric-accumulate) with
  buffer donation; gradients are globally correct because the batch is a
  mesh-sharded global array (no DDP/allreduce calls to orchestrate).
- the hot loop never syncs the host: boundary arithmetic is pure Python on
  step counters; metrics are fetched once per REPORT boundary.
- checkpoints write each process's addressable array shards (orbax) inside
  a CheckpointContext-managed directory; loader/callback state rides along.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable as TCallable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax.core import meta as flax_meta

from determined_tpu.config.experiment import ExperimentConfig, Length
from determined_tpu.core import _context as core_context_mod
from determined_tpu.data._loader import DataLoader, to_global
from determined_tpu.data._prefetch import EpochFeed, InputPipeline
from determined_tpu.observability import chip_peak_flops, get_tracer
from determined_tpu.parallel.mesh import MeshAxes, MeshConfig, make_mesh
from determined_tpu.parallel.sharding import (
    DEFAULT_RULES,
    param_shardings,
)
from determined_tpu.train._state import TrainState
from determined_tpu.train._trial import Callback, JaxTrial, TrialContext
from determined_tpu.train import serialization
from determined_tpu.utils import faults
from determined_tpu.utils.errors import CheckpointCorruptError, CheckpointNotFoundError

logger = logging.getLogger("determined_tpu.train")


@dataclasses.dataclass
class _PendingSave:
    """An in-flight background checkpoint: the writer thread serializes the
    on-device snapshot; ``finish`` (collective merge/upload/report) runs on
    the main thread at the next drain point."""

    thread: threading.Thread
    finish: TCallable[[], None]
    storage_id: str
    step: int
    errors: list


def init(
    *,
    hparams: Optional[Dict[str, Any]] = None,
    mesh_config: Optional[MeshConfig] = None,
    exp_config: Optional[ExperimentConfig] = None,
    core_context: Optional[Any] = None,
    seed: Optional[int] = None,
    rules: Optional[Dict[str, Any]] = None,
    devices: Optional[List[Any]] = None,
) -> TrialContext:
    """Build a TrialContext — reference ``pytorch.init`` (``_trainer.py:282``).

    Off-cluster this produces a fully local context (dummy core services);
    on-cluster the same call picks up rendezvous + master connection.
    ``devices`` restricts the trial's mesh to an explicit device subset —
    the concurrent scheduler passes each trial its gang-allocated submesh
    (default: all of ``jax.devices()``).
    """
    if exp_config is not None:
        if hparams is None:
            hparams = {
                k: getattr(v, "val", v)
                for k, v in exp_config.hyperparameters.items()
                if not isinstance(v, dict)
            }
            # nested hp dicts pass through with Consts collapsed
            for k, v in exp_config.hyperparameters.items():
                if isinstance(v, dict):
                    hparams[k] = _collapse(v)
        mesh_config = mesh_config or exp_config.resources.mesh
        if seed is None:
            seed = exp_config.reproducibility.experiment_seed
    from determined_tpu.utils.compilation_cache import setup_compilation_cache

    setup_compilation_cache(
        exp_config.optimizations.compilation_cache_dir if exp_config else None
    )
    core = core_context or core_context_mod.init()
    mesh = make_mesh(mesh_config or MeshConfig.data_parallel(-1), devices=devices)
    return TrialContext(
        core=core,
        mesh=mesh,
        hparams=hparams,
        rules=rules,
        seed=seed or 0,
        exp_config=exp_config,
    )


def _collapse(tree: Dict[str, Any]) -> Dict[str, Any]:
    return {
        k: _collapse(v) if isinstance(v, dict) else getattr(v, "val", v)
        for k, v in tree.items()
    }


def _infer_fsdp_specs(params_abstract: Any, mesh) -> Any:
    """Auto-FSDP: shard each param's largest dim divisible by the fsdp axis.

    Zero-annotation data-parallel-sharded params — the analog of ZeRO-3 via
    DeepSpeed in the reference, but done by the compiler from a spec.
    """
    fsdp = mesh.shape.get(MeshAxes.FSDP, 1)

    def spec(leaf):
        shape = leaf.shape
        if fsdp <= 1 or not shape:
            return None
        divisible = [d for d in range(len(shape)) if shape[d] % fsdp == 0 and shape[d] >= fsdp]
        if not divisible:
            return None
        d = max(divisible, key=lambda i: shape[i])
        out = [None] * len(shape)
        out[d] = "fsdp_shard"
        return tuple(out)

    return jax.tree.map(spec, params_abstract)


def _specs_from_flax_metadata(abstract_boxed: Any) -> Optional[Any]:
    """Extract logical specs from flax ``with_partitioning`` metadata."""
    leaves = jax.tree.leaves(abstract_boxed, is_leaf=lambda x: isinstance(x, flax_meta.Partitioned))
    if not any(isinstance(l, flax_meta.Partitioned) for l in leaves):
        return None
    spec_tree = nn.get_partition_spec(abstract_boxed)
    return jax.tree.map(
        lambda s: tuple(s) if s is not None and len(tuple(s)) else None,
        spec_tree,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )


class _BoundarySchedule:
    """Next-boundary arithmetic over a step counter (host-side ints only)."""

    def __init__(self, period: Optional[int], max_steps: int) -> None:
        self.period = period if period and period > 0 else None
        self.max_steps = max_steps

    def next_after(self, step: int) -> int:
        if self.period is None:
            return self.max_steps
        return min(((step // self.period) + 1) * self.period, self.max_steps)

    def is_boundary(self, step: int) -> bool:
        return step >= self.max_steps or (
            self.period is not None and step % self.period == 0
        )


class Trainer:
    """Drives a JaxTrial — reference ``Trainer`` + controller in one."""

    def __init__(self, trial: JaxTrial, context: Optional[TrialContext] = None) -> None:
        self.trial = trial
        self.context = context or trial.context
        self.core = self.context.core
        self.mesh = self.context.mesh
        self._compiled = False
        # populated by _setup
        self.model: Any = None
        self.tx: Any = None
        self.train_loader: Optional[DataLoader] = None
        self.val_loader: Optional[DataLoader] = None
        self.state: Optional[TrainState] = None
        self.callbacks: Dict[str, Callback] = {}
        self.steps_completed = 0
        self.best_validation: Optional[float] = None
        self._searcher_metric: Optional[str] = None
        self._smaller_is_better = True
        self.agg = 1  # aggregation_frequency, set from exp config in _setup
        self._pending_save: Optional[_PendingSave] = None
        self._snapshot_jit: Any = None
        self._tokens_per_sample: Optional[int] = None  # set by _setup
        self._overlap_plan: Any = None  # train/_overlap.py GradSyncPlan
        self._comm_model: Any = None    # its CommModel (step.comm ledger rows)
        self._bubble_model: Any = None  # parallel/pipeline.py BubbleModel
        #                                 (step.bubble ledger rows)
        # Newest FINALIZED checkpoint (manifest written, master reported).
        # An async save still in flight is deliberately excluded: until its
        # drain-point finalize runs it has no manifest and must never be
        # offered as a resume point.  The supervisor reads this after a
        # crash to know where the next attempt resumes from.
        self.latest_checkpoint: Optional[str] = None

    # -- setup -------------------------------------------------------------

    def _setup(self) -> None:
        ctx = self.context
        self.model = self.trial.build_model()
        self.tx = self.trial.build_optimizer()
        self.train_loader = self.trial.build_training_data_loader()
        self.val_loader = self.trial.build_validation_data_loader()
        self.callbacks = dict(self.trial.build_callbacks())
        cfg = ctx.exp_config
        if cfg is not None:
            self._searcher_metric = cfg.searcher.metric
            self._smaller_is_better = cfg.searcher.smaller_is_better
            if cfg.optimizations.fetch_workers:
                # config-level fetch_workers applies to loaders the trial
                # built without an explicit per-loader setting
                for ld in (self.train_loader, self.val_loader):
                    if ld is not None and not ld.fetch_workers:
                        ld.fetch_workers = cfg.optimizations.fetch_workers

        rng = jax.random.key(ctx.seed)
        init_rng, state_rng = jax.random.split(rng)

        sample = next(self.train_loader.iter_epoch(0))
        self._sample_host_batch = sample

        # ---- parameter shapes + logical specs (no real init yet) --------
        abstract_raw_boxed = jax.eval_shape(
            lambda r: self.trial.init_params(self.model, r, sample), init_rng
        )
        abstract_boxed = jax.eval_shape(
            self.trial.restructure_params, abstract_raw_boxed
        )
        specs = self.trial.param_logical_specs(abstract_boxed)
        if specs is None:
            specs = _specs_from_flax_metadata(abstract_boxed)
        abstract = flax_meta.unbox(abstract_boxed)
        if specs is None:
            specs = _infer_fsdp_specs(abstract, self.mesh)
        self._param_specs = specs
        shardings = param_shardings(specs, self.mesh, ctx.rules)

        # ---- metric structure from an abstract trace ---------------------
        global_sample = to_global(sample, self.mesh)
        metrics_shape = jax.eval_shape(
            lambda p, b, r: self.trial.loss(self.model, p, b, r)[1],
            abstract,
            global_sample,
            state_rng,
        )
        metric_keys = tuple(sorted(metrics_shape.keys())) + ("loss",)
        if getattr(self.trial, "lr_schedule", None) is not None:
            metric_keys = metric_keys + ("lr",)

        # ---- sharded init --------------------------------------------------
        # 1. init params, then commit them to their planned mesh shardings;
        # 2. build opt_state under jit from the *committed* params so XLA
        #    propagates the param shardings into mirror leaves (adam mu/nu);
        # 3. replicate every remaining leaf (scalars, rng) over the mesh so
        #    the whole TrainState lives on one consistent device set.
        # NO ambient mesh here: flax >= 0.10 applies each Partitioned box's
        # LOGICAL names as a sharding constraint whenever a global mesh is
        # active, and logical names are not mesh axes.  out_shardings carry
        # the mesh explicitly, so init still materializes directly sharded
        # (no single-device materialization at FSDP scale).
        from determined_tpu.parallel._compat import sharded_restack_safe

        # process_count first: the probe itself jits over a 2x2 mesh of
        # jax.devices()[:4], which on a multi-host gang spans
        # non-addressable devices and cannot be fetched
        if jax.process_count() > 1 or sharded_restack_safe():
            params = jax.jit(
                lambda r: flax_meta.unbox(
                    self.trial.restructure_params(
                        self.trial.init_params(self.model, r, sample)
                    )
                ),
                out_shardings=shardings,
            )(init_rng)
        else:
            # Affected jax (see _compat.sharded_restack_safe): a restack
            # (jnp.stack) into sharded out_shardings over a multi-axis
            # mesh SUMS the replicated operands, so a pipe>1 trial would
            # start from doubled block weights.  Stage the init: the
            # RNG-bearing phase materializes fully replicated (measured
            # correct), the restructure runs eagerly, and the reshard
            # goes through device_put (an honest transfer, not a GSPMD
            # resharding).  Single-process only — device_put refuses
            # non-addressable shardings, and the multiprocess CPU gangs
            # that would care run one device per host (< 4 devices never
            # hits the bug).
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            raw = jax.jit(
                lambda r: self.trial.init_params(self.model, r, sample),
                out_shardings=jax.tree.map(lambda _: repl, abstract_raw_boxed),
            )(init_rng)
            params = jax.device_put(
                flax_meta.unbox(self.trial.restructure_params(raw)), shardings
            )

        # ---- overlapped gradient sync plan (train/_overlap.py) -----------
        # Built whenever the mesh has gradient-reduction axes: with the
        # knob ON it carries the bucket markers + sharded layouts the step
        # uses below; either way it carries the comm model feeding the
        # goodput ledger's step.comm rows (docs/performance.md).
        opt = ctx.exp_config.optimizations if ctx.exp_config is not None else None
        from determined_tpu.train import _overlap

        self._overlap_plan = _overlap.build_plan(
            abstract,
            shardings,
            self.mesh,
            enabled=bool(opt is not None and opt.overlap_grad_sync),
            bucket_bytes=(opt.overlap_bucket_mb if opt else 4) * 1024 * 1024,
            hierarchical=bool(opt is not None and opt.hierarchical_collectives),
        )
        self._comm_model = (
            self._overlap_plan.comm if self._overlap_plan is not None else None
        )
        sync_on = self._overlap_plan is not None and self._overlap_plan.enabled

        # ---- pipeline schedule selection (parallel/pipeline.py) ----------
        # The trial declares the microbatch schedule it traces (gpipe /
        # 1f1b / interleaved); the Trainer folds it into the jit-cache key
        # below and into the goodput ledger's step.bubble rows — the
        # analytic tick model that attributes pipe-axis idle time the way
        # the CommModel attributes gradient-collective exposure.
        spec_fn = getattr(self.trial, "pipeline_schedule_spec", None)
        pipe_sched = spec_fn() if spec_fn is not None else None
        if pipe_sched is not None:
            from determined_tpu.parallel.pipeline import BubbleModel

            self._bubble_model = BubbleModel(schedule=pipe_sched)

        if opt is not None and opt.quantized_matmul != "none":
            # fail fast with a clear config error on unsupported platforms
            # (e.g. fp8 off TPU v5p/v6+), before any compile is attempted
            from determined_tpu.train._quant import require_platform

            dev0 = self.mesh.devices.flat[0]
            require_platform(
                opt.quantized_matmul,
                backend=getattr(dev0, "platform", None),
                device_kind=getattr(dev0, "device_kind", None),
            )

        if sync_on:
            # ZeRO-style memory win: the adam mirror leaves (mu/nu) live
            # SHARDED over the sync axes, matching the reduce-scattered
            # grads the update consumes — each device owns 1/n of the
            # optimizer state instead of a full replica
            abstract_opt = jax.eval_shape(self.tx.init, params)
            opt_state = jax.jit(
                self.tx.init,
                out_shardings=self._overlap_plan.opt_shardings(abstract_opt),
            )(params)
        else:
            opt_state = jax.jit(self.tx.init)(params)
        self.state = TrainState.create(params, opt_state, state_rng, metric_keys)
        self.state = self._place_on_mesh(self.state)

        # ---- jitted steps -------------------------------------------------
        trial, model, tx = self.trial, self.model, self.tx
        agg = opt.aggregation_frequency if opt else 1
        average_grads = opt.average_aggregated_gradients if opt else True
        self.agg = agg
        overlap = self._overlap_plan if sync_on else None

        def train_step(state: TrainState, batch):
            step_rng = jax.random.fold_in(state.rng, state.step)

            def loss_fn(p, mb):
                if overlap is not None and agg == 1:
                    # bucket markers: identity forward; backward pins each
                    # bucket's grads to the reduce-scattered layout at its
                    # production point (train/_overlap.py).  Under grad
                    # accumulation the sync moves AFTER the scan instead —
                    # one reduction per OPTIMIZER step, not per microbatch
                    p = overlap.mark(p)
                loss, m = trial.loss(model, p, mb, step_rng)
                return loss, m

            if agg == 1:
                (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    state.params, batch
                )
            else:
                # gradient accumulation: scan over stacked microbatches
                # [agg, batch, ...] accumulating grads on device — the
                # reference's aggregation_frequency loop
                # (_pytorch_context.py:708-914) without host round-trips
                def micro(carry, mb):
                    gacc, lacc, macc = carry
                    (l, m), g = jax.value_and_grad(loss_fn, has_aux=True)(
                        state.params, mb
                    )
                    gacc = jax.tree.map(jnp.add, gacc, g)
                    macc = {k: macc[k] + m[k].astype(jnp.float32) for k in macc}
                    return (gacc, lacc + l, macc), None

                g0 = jax.tree.map(jnp.zeros_like, state.params)
                m0 = {
                    k: jnp.zeros((), jnp.float32)
                    for k in state.metric_acc
                    if k not in ("loss", "lr")  # synthesized post-scan
                }
                (grads, loss, metrics), _ = jax.lax.scan(
                    micro, (g0, jnp.zeros((), jnp.float32), m0), batch
                )
                loss = loss / agg
                metrics = {k: v / agg for k, v in metrics.items()}
                if average_grads:
                    grads = jax.tree.map(lambda g: g / agg, grads)
                if overlap is not None:
                    # sync the ACCUMULATED grads once — inside the scan the
                    # markers would issue agg collectives per optimizer step
                    grads = overlap.apply_grad_sync(grads)
            if hasattr(tx, "apply_step"):
                # fused full-step optimizer (ops/fused_adamw.py): produces
                # new params directly — materializing an updates tree would
                # cost two extra HBM passes on a bandwidth-bound step
                new_params, new_opt = tx.apply_step(grads, state.opt_state, state.params)
            else:
                updates, new_opt = tx.update(grads, state.opt_state, state.params)
                new_params = optax.apply_updates(state.params, updates)
            if overlap is not None:
                # the closing all-gather: sharded update back to the
                # params' own layout; opt state pinned so donated buffers
                # round-trip with stable shardings step over step
                new_params = overlap.restore_params(new_params)
                new_opt = overlap.pin_opt_state(new_opt)
            metrics = dict(metrics)
            metrics["loss"] = loss
            # schedule-state surfacing (reference LRScheduler wrapper): a
            # trial exposing `lr_schedule` (an optax schedule callable)
            # gets its current learning rate reported with every batch
            schedule = getattr(trial, "lr_schedule", None)
            if schedule is not None:
                metrics["lr"] = schedule(state.step).astype(jnp.float32)
            acc = {
                k: state.metric_acc[k] + metrics[k].astype(jnp.float32)
                for k in state.metric_acc
            }
            return state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                metric_acc=acc,
                metric_count=state.metric_count + 1.0,
            )

        from determined_tpu.train._reducer import MEAN, get_reducer

        reducers = {k: get_reducer(v) for k, v in trial.evaluation_reducers().items()}
        self._reducers = reducers

        def eval_step(params, batch, acc, count):
            metrics = trial.evaluate_batch(model, params, batch)
            new_acc = {}
            for k, v in metrics.items():
                red = reducers.get(k, MEAN)
                carry = acc.get(k, jnp.asarray(red.init, jnp.float32))
                new_acc[k] = red.accumulate(carry, v.astype(jnp.float32))
            return new_acc, count + 1.0

        # ---- retrace sentinel (lint/_runtime.py) -------------------------
        # Wrapping happens BEFORE jit: jax then calls the wrapper once per
        # trace, so the count is the compile count for this callable.  A
        # train step traces once; an eval step twice (first validation
        # batch sees an empty metric accumulator, later ones a populated
        # one).  Anything beyond that is a silent recompile the sentinel
        # logs — exactly what the jit-reuse cache exists to prevent.
        from determined_tpu.lint._runtime import get_retrace_sentinel

        sentinel = get_retrace_sentinel()
        use_sentinel = sentinel.enabled or (
            ctx.exp_config is not None
            and getattr(ctx.exp_config, "lint", None) is not None
            and ctx.exp_config.lint.retrace_sentinel
        )
        if use_sentinel:
            label = f"{type(trial).__module__}:{type(trial).__qualname__}"
            train_step = sentinel.wrap(f"{label}.train_step", train_step, allowed=1)
            eval_step = sentinel.wrap(f"{label}.eval_step", eval_step, allowed=2)

        # ---- cross-trial jit reuse ---------------------------------------
        # Same-architecture trials in one process (the concurrent search
        # scheduler, sequential ASHA backfills) share ONE jitted callable
        # per step signature instead of re-tracing/re-compiling identical
        # programs — see train/_jit_cache.py for exactly what keys the
        # signature and why sharing is sound.
        from determined_tpu.train import _jit_cache

        use_cache = opt.jit_cache if opt is not None else True
        if use_cache:
            key = _jit_cache.step_cache_key(
                trial=trial,
                hparams=ctx.hparams,
                mesh=self.mesh,
                agg=agg,
                average_grads=average_grads,
                sample_batch=sample,
                metric_keys=metric_keys,
                rules=ctx.rules,
                # both knobs reshape the traced program (collective
                # structure / matmul arithmetic): toggling either must
                # never serve a stale trace
                overlap=(
                    self._overlap_plan.fingerprint()
                    if self._overlap_plan is not None
                    else "overlap:none"
                ),
                quant=opt.quantized_matmul if opt else "none",
                # the microbatch schedule + virtual-stage count reshape
                # the traced program (trip counts, param layout, custom
                # backward): toggling must never serve a stale trace
                pipeline=(
                    pipe_sched.fingerprint()
                    if pipe_sched is not None
                    else "pipe:none"
                ),
            )
            cache = _jit_cache.get_step_cache()
            entry = cache.lookup(key)
            if entry is None:
                train_jit = jax.jit(train_step, donate_argnums=0)
                entry = cache.insert(
                    key,
                    _jit_cache.CachedSteps(
                        train_step=_jit_cache.timed_first_call(
                            train_jit, "jit.compile.train"
                        ),
                        eval_step=_jit_cache.timed_first_call(
                            jax.jit(eval_step, donate_argnums=2), "jit.compile.eval"
                        ),
                        trial_class=f"{type(trial).__module__}:{type(trial).__qualname__}",
                        train_jit=train_jit,
                    ),
                )
            else:
                logger.info(
                    "jit-reuse cache hit for %s (key %s…): sharing compiled "
                    "train/eval steps",
                    type(trial).__qualname__,
                    key[:12],
                )
            self._train_step = entry.train_step
            self._eval_step = entry.eval_step
            self._train_step_jit = entry.train_jit
        else:
            self._train_step_jit = jax.jit(train_step, donate_argnums=0)
            self._train_step = _jit_cache.timed_first_call(
                self._train_step_jit, "jit.compile.train"
            )
            self._eval_step = _jit_cache.timed_first_call(
                jax.jit(eval_step, donate_argnums=2), "jit.compile.eval"
            )

        # ---- goodput-ledger context (observability/_goodput.py) ----------
        # tokens/MFU in the ledger need per-step token counts and the
        # device roofline; both are best-effort — a trial without a known
        # tokens-per-sample simply reports samples/s only
        self._tokens_per_sample = getattr(trial, "tokens_per_sample", None) or (
            (ctx.hparams or {}).get("seq_len")
            if isinstance((ctx.hparams or {}).get("seq_len"), int)
            else None
        )
        tracer = get_tracer()
        if tracer.enabled:
            dev = self.mesh.devices.flat[0]
            # default=0: an unknown chip (CPU tests) reports no roofline
            # rather than a bogus MFU against a TPU peak
            peak = chip_peak_flops(getattr(dev, "device_kind", ""), default=0.0)
            if peak:
                tracer.gauge(
                    "device.peak_flops_total", peak * float(self.mesh.devices.size)
                )
            fpt = getattr(trial, "flops_per_token", None)
            if fpt:
                tracer.gauge("train.flops_per_token", float(fpt))
            if self._bubble_model is not None:
                # static schedule facts for the ledger: the modeled idle
                # fraction and the tick counts behind it
                tracer.gauge(
                    "step.bubble.fraction", float(self._bubble_model.fraction)
                )
                tracer.gauge(
                    "step.bubble.ticks_total",
                    float(self._bubble_model.schedule.total_ticks),
                )
                tracer.gauge(
                    "step.bubble.ticks_idle",
                    float(self._bubble_model.schedule.bubble_ticks),
                )

    def _place_on_mesh(self, tree: Any) -> Any:
        """Replicate any leaf not already sharded over THIS mesh.

        Multi-process: ``device_put`` refuses non-addressable shardings, so
        replication goes through ``make_array_from_callback`` (every process
        supplies its addressable replicas from the host value).
        """
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(self.mesh, PartitionSpec())
        multiprocess = jax.process_count() > 1

        def fix(x):
            if not isinstance(x, jax.Array):
                return x
            s = x.sharding
            if isinstance(s, NamedSharding) and s.mesh.devices.size == self.mesh.devices.size \
                    and set(d.id for d in s.mesh.devices.flat) == set(d.id for d in self.mesh.devices.flat):
                return x
            if multiprocess:
                if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
                    data = np.asarray(jax.random.key_data(x))
                    garr = jax.make_array_from_callback(
                        data.shape, repl, lambda idx: data[idx]
                    )
                    return jax.random.wrap_key_data(garr, impl=jax.random.key_impl(x))
                host = np.asarray(x)
                return jax.make_array_from_callback(host.shape, repl, lambda idx: host[idx])
            return jax.device_put(x, repl)

        return jax.tree.map(fix, tree)

    # -- input pipeline ----------------------------------------------------

    def _input_opts(self) -> Tuple[int, int]:
        """(prefetch_depth, device_prefetch) from config, defaulting to the
        overlapped pipeline (2/2 = background fetch + double buffering)."""
        opt = self.context.exp_config.optimizations if self.context.exp_config else None
        if opt is None:
            return 2, 2
        return opt.prefetch_depth, opt.device_prefetch

    # -- length arithmetic -------------------------------------------------

    def _to_batches(self, length: Optional[Length]) -> Optional[int]:
        """Convert a Length to OPTIMIZER steps.  With gradient accumulation
        each step consumes ``agg`` loader batches, so epoch/record lengths
        divide by it (a 1-epoch run is one data pass regardless of agg)."""
        if length is None:
            return None
        length = Length.parse(length)
        if length.unit == "batches":
            return length.units
        if length.unit == "epochs":
            return max(
                1, length.units * self.train_loader.batches_per_epoch // self.agg
            )
        # records
        gbs = self.train_loader.sampler.global_batch * self.agg
        return max(1, length.units // gbs)

    # -- checkpoint --------------------------------------------------------

    def _async_checkpointing(self) -> bool:
        opt = self.context.exp_config.optimizations if self.context.exp_config else None
        enabled = opt.async_checkpointing if opt is not None else True
        # Multi-process CPU gangs (devcluster) run collectives over gloo,
        # whose TCP pairs cannot carry two in-flight collectives from
        # different threads: the background writer's sync_global_devices
        # barrier interleaves with the training step's psum and aborts the
        # process (gloo EnforceNotMet preamble.length mismatch).  TPU/GPU
        # runtimes order concurrent collectives, so only CPU downgrades.
        # This is the collective-SEQUENCE hazard class the lint package's
        # CollectiveSequenceSentinel polices at runtime: every rank must
        # issue the same ops in the same order, and a second thread
        # injecting collectives breaks that contract on transports that
        # don't serialize them (docs/lint.md, "SPMD correctness").
        if enabled and jax.process_count() > 1 and jax.default_backend() == "cpu":
            return False
        return enabled

    def _snapshot_arrays(self, tree: Any) -> Any:
        """On-device copy of the array state.  The train step donates its
        input state (``donate_argnums=0``), so the buffers a background
        writer reads would be invalidated by the NEXT step — the copy
        (one HBM pass, ~ms) decouples them."""

        def copy_one(x):
            if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
                return jax.random.wrap_key_data(
                    jnp.copy(jax.random.key_data(x)), impl=jax.random.key_impl(x)
                )
            return jnp.copy(x)

        if self._snapshot_jit is None:
            self._snapshot_jit = jax.jit(lambda t: jax.tree.map(copy_one, t))
        return self._snapshot_jit(tree)

    def _drain_pending_save(self) -> Optional[str]:
        """Wait for the in-flight background save (if any) and run its
        collective finalize.  Must be called from the main thread at a
        point every rank reaches identically (next save / preempt / exit).

        Multi-rank failure semantics: before entering the collective
        finalize, every rank allgathers its writer's error flag.  A failed
        background writer on ONE rank therefore fails ALL ranks here, fast
        and together — without the exchange, the healthy ranks would enter
        the finalize collective and hang on the dead rank until the 600s
        collective timeout.  This is the canonical exchange-then-escape
        idiom the ``conditional-collective-escape`` lint rule encodes: the
        raise below is guarded by ``failed_ranks``, which is derived from
        the allgather result and therefore rank-uniform — the pass
        recognizes that and stays quiet, where a raise on the LOCAL flag
        would be flagged.  The ``checkpoint.stall`` span records how long
        training sat blocked on the drain either way.
        """
        p = self._pending_save
        if p is None:
            return None
        self._pending_save = None
        tracer = get_tracer()
        stall_t0 = time.monotonic()
        p.thread.join()
        failed = bool(p.errors)
        dist = self.core.distributed
        if dist.size > 1:
            flags = dist.allgather(failed)
            failed_ranks = [r for r, f in enumerate(flags) if f]
        else:
            failed_ranks = [0] if failed else []
        tracer.record_span(
            "checkpoint.stall",
            "checkpoint",
            stall_t0,
            time.monotonic(),
            {"storage_id": p.storage_id, "failed_ranks": failed_ranks},
        )
        if failed_ranks:
            if p.errors:
                raise RuntimeError(
                    f"async checkpoint {p.storage_id} failed "
                    f"(ranks {failed_ranks})"
                ) from p.errors[0]
            raise RuntimeError(
                f"async checkpoint {p.storage_id} failed on rank(s) "
                f"{failed_ranks}; failing fast before the collective finalize"
            )
        with tracer.span("checkpoint.finalize", cat="checkpoint", storage_id=p.storage_id):
            p.finish()
        self.latest_checkpoint = p.storage_id
        for cb in self.callbacks.values():
            cb.on_checkpoint_write_end(p.storage_id)
        logger.info("checkpoint %s at step %d", p.storage_id, p.step)
        return p.storage_id

    def _save_checkpoint(self, asynchronous: bool = True) -> str:
        self._drain_pending_save()  # at most one save in flight
        dist = self.core.distributed
        shard = dist.size > 1
        array_state = {
            "step": self.state.step,
            "params": self.state.params,
            "opt_state": self.state.opt_state,
            "rng": self.state.rng,
        }
        trainer_state = {
            "steps_completed": self.steps_completed,
            "train_loader": self.train_loader.state_dict(),
            "callbacks": {k: cb.state_dict() for k, cb in self.callbacks.items()},
            "best_validation": self.best_validation,
            # rebuild-from-checkpoint info (reference pytorch/_load.py):
            # enough to reconstruct the Trial without the experiment
            "trial_class": f"{type(self.trial).__module__}:{type(self.trial).__qualname__}",
            "hparams": dict(self.context.hparams),
            "exp_config": self.context.exp_config.raw if self.context.exp_config else None,
            "seed": self.context.seed,
            # mesh the arrays were sharded over when written — a restore onto
            # a different mesh (elastic reshard) is detected by comparing this
            # against the live mesh and recorded as a ``trial.resize`` span
            "mesh_axes": {k: int(v) for k, v in self.mesh.shape.items()},
        }
        metadata = {
            "steps_completed": self.steps_completed,
            "framework": "determined_tpu",
            # lineage pointer: lets a resume that finds THIS checkpoint
            # corrupt fall back to the previous good one (the manifest
            # carries a copy; this survives a kill before the manifest)
            "parent_storage_id": self.latest_checkpoint,
        }
        if not (asynchronous and self._async_checkpointing()):
            with get_tracer().span(
                "checkpoint.save", cat="checkpoint", mode="sync", step=self.steps_completed
            ):
                with self.core.checkpoint.store_path(metadata, shard=shard) as (path, sid):
                    for cb in self.callbacks.values():
                        cb.on_checkpoint_write_start(path)
                    serialization.save_arrays(path, array_state)
                    if dist.is_chief:
                        serialization.save_trainer_state(path, trainer_state)
            self.latest_checkpoint = sid
            for cb in self.callbacks.values():
                cb.on_checkpoint_write_end(sid)
            logger.info("checkpoint %s at step %d", sid, self.steps_completed)
            return sid

        # overlapped save: snapshot on device, serialize on a background
        # thread, collective finalize at the next drain point (SURVEY §7(b))
        with get_tracer().span(
            "checkpoint.dispatch", cat="checkpoint", step=self.steps_completed
        ):
            path, sid, finish = self.core.checkpoint.store_path_async(metadata, shard=shard)
            for cb in self.callbacks.values():
                cb.on_checkpoint_write_start(path)
            snapshot = self._snapshot_arrays(array_state)
        is_chief = dist.is_chief
        errors: list = []

        def work() -> None:
            try:
                with get_tracer().span(
                    "checkpoint.write", cat="checkpoint", storage_id=sid
                ):
                    serialization.save_arrays(path, snapshot)
                    if is_chief:
                        serialization.save_trainer_state(path, trainer_state)
            except BaseException as e:  # surfaced at the drain point
                # single background writer; the drain point joins this
                # thread BEFORE reading errors (happens-before via join)
                errors.append(e)  # dtpu: lint-ok[unlocked-shared-state]

        thread = threading.Thread(target=work, name="dtpu-ckpt-writer", daemon=True)
        thread.start()
        self._pending_save = _PendingSave(
            thread=thread, finish=finish, storage_id=sid,
            step=self.steps_completed, errors=errors,
        )
        logger.info("async checkpoint %s started at step %d", sid, self.steps_completed)
        return sid

    def _verify_on_restore(self) -> bool:
        cfg = self.context.exp_config
        ft = getattr(cfg, "fault_tolerance", None) if cfg is not None else None
        return ft.verify_checkpoints if ft is not None else True

    def _restore_checkpoint(self, storage_id: str) -> None:
        """Restore with manifest verification, walking the parent lineage
        on corruption.

        Trainer-written checkpoints always end finalize with a manifest,
        so resume requires one (``require_manifest=True``): a checkpoint
        whose writer died mid-upload has no manifest and is rejected, and
        a truncated/bit-flipped file fails the size/md5 check — either way
        the restore falls back to the checkpoint's recorded parent instead
        of silently resuming from poison (reference: the master only ever
        resumes from checkpoints it recorded as COMPLETED).
        """
        verify = self._verify_on_restore()
        sid: Optional[str] = storage_id
        tried = []
        while sid:
            try:
                with self.core.checkpoint.restore_path(
                    sid, verify=verify, require_manifest=verify
                ) as path:
                    self.restore_from_path(path)
                self.latest_checkpoint = sid
                if tried:
                    logger.warning(
                        "resumed from fallback checkpoint %s (rejected: %s)",
                        sid,
                        ", ".join(tried),
                    )
                logger.info("restored checkpoint %s at step %d", sid, self.steps_completed)
                return
            except (CheckpointCorruptError, CheckpointNotFoundError) as e:
                logger.warning("checkpoint %s unusable for resume: %s", sid, e)
                tried.append(sid)
                parent = self.core.checkpoint.get_checkpoint_parent(sid)
                if parent in tried:
                    break  # defensive: a lineage cycle must not loop forever
                sid = parent
        raise CheckpointCorruptError(
            f"no usable checkpoint in lineage of {storage_id} "
            f"(tried: {', '.join(tried)}); checkpoints written before the "
            "manifest era can be resumed by setting "
            "fault_tolerance.verify_checkpoints: false"
        )

    def _restore_checkpoint_traced(self, storage_id: str) -> None:
        """Resume replay, recorded as a ``restore`` span — the goodput
        ledger's "time spent re-reaching the pre-crash state" bucket."""
        with get_tracer().span("checkpoint.restore", cat="restore", storage_id=storage_id):
            self._restore_checkpoint(storage_id)

    def restore_from_path(self, path: str) -> None:
        """Load arrays + trainer state from an already-local checkpoint dir
        (``_restore_checkpoint`` handles storage download; this is the shared
        tail, also used by ``train.load_trial_from_checkpoint``).

        The checkpoint may have been written on a DIFFERENT mesh (elastic
        reshard): ``abstract_like`` targets the *current* state's shardings,
        so orbax re-lays every array — params and the sharded optimizer
        mirrors alike — onto the live mesh, and the loader rescales its
        consumed-sample position if the global batch changed.  A cross-mesh
        restore is wrapped in a ``trial.resize`` span so the profile
        attributes the reshard window."""
        tstate = serialization.load_trainer_state(path)
        stored_axes = tstate.get("mesh_axes")
        cur_axes = {k: int(v) for k, v in self.mesh.shape.items()}
        resizing = stored_axes is not None and (
            {k: int(v) for k, v in stored_axes.items()} != cur_axes
        )
        if not resizing:
            self._restore_tail(path, tstate)
            return
        fmt = lambda ax: ",".join(f"{k}={v}" for k, v in ax.items())  # noqa: E731
        logger.info(
            "elastic reshard: restoring checkpoint written on mesh (%s) "
            "onto mesh (%s)", fmt(stored_axes), fmt(cur_axes),
        )
        with get_tracer().span(
            "trial.resize",
            cat="restore",
            from_mesh=fmt(stored_axes),
            to_mesh=fmt(cur_axes),
        ):
            self._restore_tail(path, tstate)

    def _restore_tail(self, path: str, tstate: Dict[str, Any]) -> None:
        abstract = serialization.abstract_like(
            {
                "step": self.state.step,
                "params": self.state.params,
                "opt_state": self.state.opt_state,
                "rng": self.state.rng,
            }
        )
        fresh_opt_state = self.state.opt_state
        restored = serialization.restore_arrays(path, abstract)
        self.state = self.state.replace(**restored).reset_metrics()
        # declared-runtime hyperparameters (compile_cache_runtime_hparams,
        # e.g. an inject_hyperparams lr) live in opt_state, so a restore
        # would resurrect the CHECKPOINT's values — correct for a crash
        # resume (same hparams), wrong for a PBT clone whose explore step
        # just perturbed them.  The trial's own hparams are authoritative:
        # graft the freshly-built hyperparams back over the restored tree.
        self.state = self.state.replace(
            opt_state=self._reinject_runtime_hparams(
                fresh_opt_state, self.state.opt_state
            )
        )
        self.steps_completed = int(tstate["steps_completed"])
        self.train_loader.load_state_dict(tstate["train_loader"])
        for k, cb in self.callbacks.items():
            cb.load_state_dict(tstate.get("callbacks", {}).get(k, {}))
        self.best_validation = tstate.get("best_validation")
        for cb in self.callbacks.values():
            cb.on_checkpoint_load(path)

    def _reinject_runtime_hparams(self, fresh: Any, restored: Any) -> Any:
        """Replace ``hyperparams`` nodes (optax ``InjectHyperparamsState``)
        in a restored opt_state with the freshly-initialized ones, which
        were built from THIS trial's hparams.  No-op unless the trial
        declares runtime hparams."""
        runtime = getattr(self.trial, "compile_cache_runtime_hparams", tuple)() or ()
        if not runtime:
            return restored

        def graft(f: Any, r: Any) -> Any:
            if type(f) is not type(r):
                return r
            if hasattr(r, "hyperparams") and hasattr(r, "_replace"):
                out = r._replace(hyperparams=f.hyperparams)
                if hasattr(r, "inner_state"):
                    out = out._replace(inner_state=graft(f.inner_state, r.inner_state))
                return out
            if isinstance(r, (tuple, list)) and len(f) == len(r):
                parts = [graft(a, b) for a, b in zip(f, r)]
                if hasattr(r, "_fields"):  # other namedtuple states
                    return type(r)(*parts)
                return type(r)(parts) if isinstance(r, list) else tuple(parts)
            return r

        return graft(fresh, restored)

    # -- validation --------------------------------------------------------

    def _validate(self) -> Dict[str, float]:
        for cb in self.callbacks.values():
            cb.on_validation_start()
        acc: Dict[str, jax.Array] = {}
        count = jnp.zeros((), jnp.float32)
        # the validation sweep gets the same overlap as training: host fetch
        # on a worker, eager to_global one batch ahead of the eval step
        prefetch_depth, device_buffer = self._input_opts()
        with EpochFeed(
            self.val_loader.iter_epoch(0),
            self.mesh,
            prefetch_depth=prefetch_depth,
            device_buffer=device_buffer,
        ) as feed:
            with self.mesh:
                for batch in feed:
                    acc, count = self._eval_step(self.state.params, batch, acc, count)
        from determined_tpu.train._reducer import MEAN

        acc_host, n = jax.device_get((acc, count))
        metrics = (
            {
                k: float(self._reducers.get(k, MEAN).finalize(float(v), float(n)))
                for k, v in acc_host.items()
            }
            if n
            else {}
        )
        if self.core.distributed.is_chief:
            self.core.train.report_validation_metrics(self.steps_completed, metrics)
        for cb in self.callbacks.values():
            cb.on_validation_end(metrics)
        return metrics

    def _is_best(self, metrics: Dict[str, float]) -> bool:
        name = self._searcher_metric or "validation_loss"
        if name not in metrics:
            return True  # nothing to compare on; treat as best
        val = metrics[name]
        if self.best_validation is None:
            self.best_validation = val
            return True
        better = val < self.best_validation if self._smaller_is_better else val > self.best_validation
        if better:
            self.best_validation = val
        return better

    # -- the loop ----------------------------------------------------------

    def fit(
        self,
        max_length: Any,
        *,
        validation_period: Optional[Any] = None,
        checkpoint_period: Optional[Any] = None,
        report_period: Optional[Any] = None,
        latest_checkpoint: Optional[str] = None,
        checkpoint_policy: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Train until ``max_length``; returns a summary dict."""
        tracer = get_tracer()
        with tracer.span("trainer.setup", cat="setup"):
            self._setup()
        if checkpoint_policy is None:
            cfg = self.context.exp_config
            checkpoint_policy = cfg.checkpoint_policy if cfg is not None else "best"

        max_steps = self._to_batches(Length.parse(max_length))
        val_sched = _BoundarySchedule(self._to_batches(validation_period), max_steps)
        ckpt_sched = _BoundarySchedule(self._to_batches(checkpoint_period), max_steps)
        rep_period = self._to_batches(report_period)
        if rep_period is None:
            rep_period = min(100, max(1, max_steps // 10))
        rep_sched = _BoundarySchedule(rep_period, max_steps)

        if latest_checkpoint:
            self._restore_checkpoint_traced(latest_checkpoint)

        # bounded trace window: a whole-run xplane capture grows without
        # limit, so tracing stops after profiling.end_after_batch steps —
        # counted from the RESUME point (computed after checkpoint restore
        # so restarts trace their own window, not an already-expired one)
        prof_cfg = (self.context.exp_config.profiling
                    if self.context.exp_config else None) or {}
        self._trace_stop_step = (
            self.steps_completed + int(prof_cfg.get("end_after_batch", 10))
            if prof_cfg.get("trace")
            else None
        )

        for cb in self.callbacks.values():
            cb.on_training_start(self)

        # overlapped input feed (docs/input-pipeline.md): host fetch runs on
        # a background worker, to_global on batch N+1 dispatches while step N
        # executes; __next__ commits the loader's CONSUMED position, so the
        # state_dict a checkpoint captures is exact regardless of how far
        # ahead the worker fetched
        prefetch_depth, device_buffer = self._input_opts()
        pipeline = InputPipeline(
            self.train_loader,
            self.mesh,
            agg=self.agg,
            prefetch_depth=prefetch_depth,
            device_buffer=device_buffer,
        )
        gbs = self.train_loader.sampler.global_batch * self.agg

        try:
            self._fit_loop(
                pipeline, max_steps, val_sched, ckpt_sched, rep_sched,
                checkpoint_policy, gbs,
            )
        finally:
            # the worker must die with the loop: on clean exit, preemption,
            # AND a crash unwinding toward the supervisor restart path —
            # restarts build fresh Trainers, so anything left running here
            # would accumulate across attempts
            pipeline.close()
            for ld in (self.train_loader, self.val_loader):
                if ld is not None:
                    ld.close()

        # a save still in flight must land before we exit or report completion
        self._drain_pending_save()

        # final: always leave at least one checkpoint unless policy is none
        if checkpoint_policy != "none" and self._last_ckpt_sid is None:
            self._last_ckpt_sid = self._save_checkpoint(asynchronous=False)

        for cb in self.callbacks.values():
            cb.on_trial_shutdown()

        return {
            "steps_completed": self.steps_completed,
            "latest_checkpoint": self._last_ckpt_sid,
            "validation_metrics": self._last_val_metrics,
            "stopped_early": self._stopped_early,
            "best_validation": self.best_validation,
        }

    def _fit_loop(
        self,
        pipeline: InputPipeline,
        max_steps: int,
        val_sched: _BoundarySchedule,
        ckpt_sched: _BoundarySchedule,
        rep_sched: _BoundarySchedule,
        checkpoint_policy: str,
        gbs: int,
    ) -> None:
        # lazy import, same convention as the retrace sentinel in _setup:
        # the trainer must not pull the lint analyzer package in at module
        # import time just for the (usually disabled) runtime hook
        from determined_tpu.lint._runtime import get_collective_sentinel

        cseq = get_collective_sentinel()
        tracer = get_tracer()
        hot_time = 0.0  # train-segment wall time since last report (excludes
        # validation/checkpoint so samples_per_second tracks training only)
        steps_since_report = 0
        self._last_ckpt_sid = None
        self._last_val_metrics = {}
        self._stopped_early = False
        epoch_seen = self.train_loader.epoch

        while self.steps_completed < max_steps:
            next_stop = min(
                val_sched.next_after(self.steps_completed),
                ckpt_sched.next_after(self.steps_completed),
                rep_sched.next_after(self.steps_completed),
                max_steps,
            )
            if (
                self._trace_stop_step is not None
                and self.core.profiler.tracing
                and self._trace_stop_step > self.steps_completed
            ):
                # break the hot segment at the trace boundary so the
                # capture window is end_after_batch steps, not
                # end_after_batch rounded up to the next report period
                next_stop = min(next_stop, self._trace_stop_step)
            # ---- hot segment: no host syncs ------------------------------
            seg_t0 = time.monotonic()
            seg_start_step = self.steps_completed
            # the mesh context makes trace-time sharding constraints resolve
            # for models that annotate activations without an explicit mesh
            with self.mesh:
                if tracer.enabled:
                    # traced twin of the loop below: two extra clock reads
                    # + two lock-free ring pushes per step attribute the
                    # step's wall-clock to input wait vs. step dispatch
                    # (DTPU_BENCH_TRACE measures this at <2% step time);
                    # the untraced branch stays byte-identical to before
                    while self.steps_completed < next_stop:
                        faults.fire("train.step", step=self.steps_completed)
                        t0 = time.monotonic()
                        batch = next(pipeline)
                        t1 = time.monotonic()
                        self.state = self._train_step(self.state, batch)
                        t2 = time.monotonic()
                        tracer.record_span("data.wait", "data", t0, t1)
                        tracer.record_span("step.dispatch", "step", t1, t2)
                        self.steps_completed += 1
                        steps_since_report += 1
                else:
                    while self.steps_completed < next_stop:
                        # fault-injection hook: tests crash a step here to
                        # exercise the supervised-restart path (no-op in prod)
                        faults.fire("train.step", step=self.steps_completed)
                        # already a device-global array; the pipeline stacked
                        # microbatches (agg > 1) and committed consumed state
                        batch = next(pipeline)
                        self.state = self._train_step(self.state, batch)
                        self.steps_completed += 1
                        steps_since_report += 1
            hot_time += time.monotonic() - seg_t0
            # collective-sequence sentinel: each dispatched step carries the
            # tensor-plane psums, so the SEGMENT boundary (which steps this
            # rank dispatched) is the dispatch-site signature — folded into
            # the rolling digest here, once per boundary (not per step),
            # and verified at the next control-plane exchange.  One attr
            # check when the sentinel is not installed.
            if cseq.installed:
                cseq.record(
                    self.core.distributed,
                    "step.segment",
                    f"{seg_start_step}-{self.steps_completed}",
                )
            if self.train_loader.epoch != epoch_seen:
                for e in range(epoch_seen, self.train_loader.epoch):
                    for cb in self.callbacks.values():
                        cb.on_epoch_end(e)
                epoch_seen = self.train_loader.epoch

            at_end = self.steps_completed >= max_steps
            if (
                self._trace_stop_step is not None
                and self.core.profiler.tracing
                and self.steps_completed >= self._trace_stop_step
            ):
                self.core.profiler.stop_trace()

            # ---- REPORT ---------------------------------------------------
            if rep_sched.is_boundary(self.steps_completed) or at_end:
                sync_t0 = time.monotonic()
                metrics = self.state.fetch_metrics()  # one host sync
                sync_t1 = time.monotonic()
                hot_time += sync_t1 - sync_t0
                # the boundary fetch is where the host finally waits for
                # every dispatched step — the device-compute proxy on the
                # host timeline (cat "step": productive in the ledger)
                tracer.record_span("step.boundary_block", "step", sync_t0, sync_t1)
                if steps_since_report:
                    tracer.counter("train.steps", float(steps_since_report))
                    tracer.counter("train.samples", float(steps_since_report * gbs))
                    if self._tokens_per_sample:
                        tracer.counter(
                            "train.tokens",
                            float(steps_since_report * gbs * self._tokens_per_sample),
                        )
                    if self._comm_model is not None:
                        # step.comm ledger rows (observability/_goodput.py):
                        # measured payload bytes, exposed/hidden split from
                        # the bucket-schedule model against the segment's
                        # average step time (counters, not spans — they
                        # must not perturb the span-nesting attribution)
                        hops = self._comm_model.split_hops(
                            hot_time / steps_since_report
                        )
                        n = float(steps_since_report)
                        tracer.counter(
                            "step.comm.bytes",
                            float(self._comm_model.total_bytes_per_step) * n,
                        )
                        exposed_s = sum(e for e, _ in hops.values())
                        hidden_s = sum(h for _, h in hops.values())
                        tracer.counter("step.comm.exposed_us", exposed_s * 1e6 * n)
                        tracer.counter("step.comm.hidden_us", hidden_s * 1e6 * n)
                        # per-hop rows: the DCN hop only exists on a
                        # multi-slice mesh; zero rows are suppressed so
                        # single-slice ledgers look exactly as before
                        hop_bytes = {
                            "ici": self._comm_model.bytes_per_step,
                            "dcn": self._comm_model.dcn_bytes_per_step,
                        }
                        for hop, (he, hh) in hops.items():
                            if not hop_bytes[hop]:
                                continue
                            tracer.counter(
                                f"step.comm.{hop}.bytes", float(hop_bytes[hop]) * n
                            )
                            tracer.counter(f"step.comm.{hop}.exposed_us", he * 1e6 * n)
                            tracer.counter(f"step.comm.{hop}.hidden_us", hh * 1e6 * n)
                    if self._bubble_model is not None:
                        # step.bubble ledger rows: pipe-axis idle time per
                        # the schedule's analytic tick model applied to
                        # the segment's average step time (counters, like
                        # step.comm, so span-nesting attribution stays
                        # intact)
                        bubble_s, _ = self._bubble_model.split(
                            hot_time / steps_since_report
                        )
                        tracer.counter(
                            "step.bubble.exposed_us",
                            bubble_s * 1e6 * float(steps_since_report),
                        )
                self.state = self.state.reset_metrics()
                metrics["samples_per_second"] = steps_since_report * gbs / max(hot_time, 1e-9)
                hot_time = 0.0
                steps_since_report = 0
                # metrics are identical on every rank (global-array math);
                # only the chief reports (reference: chief-only report_*)
                if self.core.distributed.is_chief:
                    self.core.train.report_training_metrics(self.steps_completed, metrics)
                    self.core.train.report_progress(self.steps_completed / max_steps)
                for cb in self.callbacks.values():
                    cb.on_training_workload_end(self.steps_completed, metrics)

            # ---- VALIDATE -------------------------------------------------
            validated = False
            if val_sched.period is not None and (
                val_sched.is_boundary(self.steps_completed) or at_end
            ):
                with tracer.span("validate", cat="validate", step=self.steps_completed):
                    self._last_val_metrics = self._validate()
                validated = True

            # ---- CHECKPOINT ----------------------------------------------
            want_ckpt = ckpt_sched.period is not None and ckpt_sched.is_boundary(
                self.steps_completed
            )
            if validated and checkpoint_policy == "all":
                want_ckpt = True
            if validated and checkpoint_policy == "best" and self._is_best(self._last_val_metrics):
                want_ckpt = True
            # ---- PREEMPT --------------------------------------------------
            preempted = self.core.preempt.should_preempt()
            if preempted:
                want_ckpt = True
            if want_ckpt:
                pending = self._pending_save
                if (
                    preempted
                    and pending is not None
                    and pending.step == self.steps_completed
                    and not pending.errors
                ):
                    # a save of this exact step is already in flight:
                    # wait for it instead of writing a duplicate
                    self._last_ckpt_sid = self._drain_pending_save()
                else:
                    # on preemption the save must be durable before exit,
                    # so skip the overlap and write synchronously
                    self._last_ckpt_sid = self._save_checkpoint(asynchronous=not preempted)
            if preempted:
                logger.info("preempted at step %d; exiting cleanly", self.steps_completed)
                self._stopped_early = True
                # should_preempt() IS the exchange: under WorkersAskChief it
                # allgathers every rank's flag, so `preempted` is identical
                # on all ranks and the whole gang breaks on the same step
                break  # dtpu: lint-ok[conditional-collective-escape]
