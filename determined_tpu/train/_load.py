"""Rebuild a Trial + Trainer from a stored checkpoint, no cluster needed.

Reference: ``harness/determined/pytorch/_load.py``
(``load_trial_from_checkpoint_path``) — there the checkpoint carries the
experiment config and code; here the trainer writes ``trial_class``,
``hparams``, ``exp_config`` and ``seed`` into its state file
(``_trainer.py _save_checkpoint``), so inference/fine-tune scripts can do::

    trial, trainer = train.load_trial_from_checkpoint("/ckpts/<uuid>")
    logits = trainer.predict(batch)
"""

from __future__ import annotations

import importlib
from typing import Any, Optional, Tuple

from determined_tpu.train import serialization
from determined_tpu.train._trainer import Trainer, init as train_init
from determined_tpu.train._trial import JaxTrial


def load_trial_from_checkpoint(
    path: str,
    trial_class: Optional[type] = None,
    mesh_config: Any = None,
    core_context: Any = None,
) -> Tuple[JaxTrial, Trainer]:
    """Reconstruct the Trial and a ready Trainer from a local checkpoint dir.

    ``trial_class`` overrides the recorded class (use when the original
    module isn't importable).  The returned trainer has params/opt state/rng
    restored at the checkpoint's step; call ``trainer.fit`` to continue
    training or use the restored ``trainer.state.params`` directly.
    """
    tstate = serialization.load_trainer_state(path)
    if trial_class is None:
        ref = tstate.get("trial_class")
        if not ref or ":" not in ref:
            raise ValueError(
                "checkpoint does not record its trial class; pass trial_class="
            )
        module_name, _, qualname = ref.partition(":")
        obj: Any = importlib.import_module(module_name)
        for part in qualname.split("."):
            obj = getattr(obj, part)
        trial_class = obj

    from determined_tpu import core
    from determined_tpu.config.experiment import ExperimentConfig

    exp_config = (
        ExperimentConfig.parse(tstate["exp_config"])
        if tstate.get("exp_config")
        else None
    )
    ctx = train_init(
        hparams=tstate.get("hparams") or {},
        exp_config=exp_config,
        mesh_config=mesh_config,
        core_context=core_context or core._dummy_init(),
        seed=int(tstate.get("seed") or 0),
    )
    trial = trial_class(ctx)
    trainer = Trainer(trial)
    trainer._setup()
    trainer.restore_from_path(path)
    return trial, trainer
