"""Overlapped gradient synchronization: bucketed reduce-scatter in backward.

Motivation (BASELINE.md r3 roofline, docs/performance.md): with matmul
fusions at 85-88% of peak and the optimizer at its bandwidth roofline, the
remaining step-time lever on the gradient path is *structural* — the
single end-of-backward gradient reduction over the data/fsdp axes sits on
the critical path with nothing left to hide behind.  The Megatron-LM /
ZeRO recipe restructures it: issue the gradient collectives per-bucket as
backward products become available, keep the optimizer consuming SHARDED
gradients and state (reduce-scatter -> sharded update -> all-gather
params), and let the scheduler interleave the collectives with the
remaining backward compute.

The XLA/jax-native expression of that recipe (this module):

- ``build_plan`` partitions the (abstract) grad pytree into size-bounded
  **buckets** in reverse-forward order — the order backward produces them;
- each bucket gets a ``custom_vjp`` identity **marker** applied to the
  params inside the loss: its backward rule pins that bucket's cotangents
  to a sharded layout over the sync axes
  (``parallel/sharding.py:grad_sync_spec``), which XLA lowers to a
  reduce-scatter at the grad's production point.  Each bucket's collective
  is an independent dataflow node (no false dependency on the other
  buckets), which is exactly what XLA's latency-hiding scheduler needs to
  interleave them with backward compute on TPU;
- the optimizer state mirrors the grad shardings (``opt_shardings`` — the
  ZeRO-1/2 memory win: mu/nu live at 1/n per device), and the updated
  params are constrained back to their own shardings, which lowers to the
  closing all-gather.  Total bytes moved equal the baseline all-reduce
  (ring RS + ring AG == ring AR); only the exposure changes;
- deliberately NOT done: concatenating a bucket's leaves into one flat
  payload (the DDP trick).  Under GSPMD the flatten/unflatten of a
  sharded payload inserts extra resharding collectives that cost more
  than the per-leaf launch overhead they save; the bucket here is the
  unit of marker arity and comm accounting, while fusion of adjacent
  small collectives is left to XLA.

Numerics: reduce-scatter + all-gather sums the same shard partials as the
all-reduce, so the step is equivalent up to float reassociation —
``tests/test_step_optimizations.py`` pins params/opt_state allclose after
N steps on the 8-device virtual mesh, and the compiled HLO contains the
expected reduce-scatter structure.

Comm accounting (``CommModel``): the goodput ledger's ``step.comm``
category is fed from an explicit bucket-schedule model — measured payload
bytes over a per-chip interconnect bandwidth, with bucket k's collective
hideable behind the backward compute of buckets k+1..B (baseline: one
bucket, nothing hides).  It is a *model* (labeled as such in the ledger);
the xplane op table stays the ground truth on real chips.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.parallel.mesh import MeshAxes
from determined_tpu.parallel.sharding import grad_sync_spec

#: axes a gradient reduction runs over: every batch-carrying axis
SYNC_AXES = MeshAxes.BATCH_AXES

#: leaves below this ride the final all-reduce: a reduce-scatter of a few
#: KiB is pure launch overhead (norm scales, biases)
_MIN_SYNC_BYTES = 64 * 1024

# Per-chip interconnect bandwidth (bytes/s, one direction) for the comm
# model — public ICI spec-sheet numbers, longest-prefix matched like the
# peak-FLOPs table in observability/_goodput.py.  DTPU_COMM_BW_GBPS
# overrides (and is the only honest choice on CPU test meshes).
ICI_BW_BY_KIND = {
    "TPU v4": 3 * 2 * 50e9,
    "TPU v5 lite": 1 * 2 * 50e9,   # v5e: 1 ICI link pair per chip side
    "TPU v5p": 3 * 2 * 100e9,
    "TPU v5": 3 * 2 * 100e9,
    "TPU v6 lite": 2 * 2 * 90e9,
    "TPU v6e": 2 * 2 * 90e9,
}
_DEFAULT_BW = 10e9  # unknown chip (CPU virtual mesh): placeholder, labeled


def _chip_bw(device_kind: str) -> float:
    env = os.environ.get("DTPU_COMM_BW_GBPS")
    if env:
        return float(env) * 1e9
    for prefix in sorted(ICI_BW_BY_KIND, key=len, reverse=True):
        if device_kind.startswith(prefix):
            return ICI_BW_BY_KIND[prefix]
    return _DEFAULT_BW


@dataclasses.dataclass(frozen=True)
class CommModel:
    """Bucket-schedule exposure model for the ``step.comm`` ledger rows."""

    bytes_per_step: int      # RS+AG (or AR) payload bytes, ring-counted
    n_buckets: int           # 1 = baseline end-of-backward reduction
    bandwidth: float         # bytes/s
    bwd_frac: float = 0.6    # share of a step that is backward compute

    def split(self, avg_step_s: float) -> Tuple[float, float]:
        """(exposed_s, hidden_s) per step under the bucket schedule.

        Baseline (one bucket): the whole reduction is exposed — backward
        is already finished when it runs.  Overlapped (B buckets): bucket
        k's collective can hide behind buckets k+1..B's backward compute,
        so up to (B-1)/B of the comm hides, bounded by the backward time
        actually available.
        """
        comm_s = self.bytes_per_step / max(self.bandwidth, 1.0)
        if self.n_buckets <= 1:
            return comm_s, 0.0
        hideable = comm_s * (self.n_buckets - 1) / self.n_buckets
        hidden = min(hideable, max(avg_step_s, 0.0) * self.bwd_frac)
        return comm_s - hidden, hidden


def _make_bucket_marker(shardings: Tuple[Optional[NamedSharding], ...]):
    """custom_vjp identity over one bucket's leaves whose backward pins
    each cotangent to its sync sharding (the reduce-scatter issue point).
    Forward is the identity, so the marker never perturbs the loss."""

    @jax.custom_vjp
    def mark(*leaves):
        return leaves

    def fwd(*leaves):
        return leaves, None

    def bwd(_, cts):
        return tuple(
            ct if s is None else jax.lax.with_sharding_constraint(ct, s)
            for ct, s in zip(cts, shardings)
        )

    mark.defvjp(fwd, bwd)
    return mark


@dataclasses.dataclass
class GradSyncPlan:
    """Everything the train step needs to overlap gradient sync.

    Built once per Trainer setup from the abstract param tree; all methods
    are trace-safe (called inside the jitted step).
    """

    mesh: Mesh
    enabled: bool
    treedef: Any
    param_shardings: List[NamedSharding]          # flat, param order
    sync_shardings: List[Optional[NamedSharding]]  # flat; None = unsynced
    buckets: List[Tuple[int, ...]]                 # leaf indices per bucket
    comm: CommModel
    synced_leaves: int
    _markers: List[Any] = dataclasses.field(default_factory=list)
    _shape_map: Dict[Tuple[int, ...], NamedSharding] = dataclasses.field(
        default_factory=dict
    )

    _leaf_shapes: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        self._markers = [
            _make_bucket_marker(tuple(self.sync_shardings[i] for i in b))
            for b in self.buckets
        ]
        # shape -> sync sharding, for optimizer-state mirror leaves.  Well
        # defined: the sync spec is a function of (shape, param spec), and
        # same-shape params get the same spec by construction.
        for i, s in enumerate(self.sync_shardings):
            if s is not None:
                self._shape_map.setdefault(self._leaf_shapes[i], s)

    def mark(self, params: Any) -> Any:
        """Apply the bucket markers to the param pytree inside the loss."""
        leaves = jax.tree.leaves(params)
        out = list(leaves)
        for marker, idxs in zip(self._markers, self.buckets):
            marked = marker(*(leaves[i] for i in idxs))
            for j, i in enumerate(idxs):
                out[i] = marked[j]
        return jax.tree.unflatten(self.treedef, out)

    def apply_grad_sync(self, grads: Any) -> Any:
        """Pin an already-accumulated grad tree to the sync shardings —
        the gradient-accumulation path, where the sync must happen ONCE
        per optimizer step on the summed grads, not per microbatch."""
        leaves = list(jax.tree.leaves(grads))
        for i, s in enumerate(self.sync_shardings):
            if s is not None:
                leaves[i] = jax.lax.with_sharding_constraint(leaves[i], s)
        return jax.tree.unflatten(self.treedef, leaves)

    def restore_params(self, new_params: Any) -> Any:
        """Constrain updated params back to their own shardings — the
        closing all-gather of the reduce-scatter/all-gather pair."""
        leaves = list(jax.tree.leaves(new_params))
        for i, s in enumerate(self.param_shardings):
            if self.sync_shardings[i] is not None:
                leaves[i] = jax.lax.with_sharding_constraint(leaves[i], s)
        return jax.tree.unflatten(self.treedef, leaves)

    def _sharding_for_shape(self, shape: Tuple[int, ...]) -> Optional[NamedSharding]:
        return self._shape_map.get(tuple(shape))

    def opt_shardings(self, abstract_opt: Any) -> Any:
        """Sharding tree for the optimizer state: param-shaped mirror
        leaves (adam mu/nu) follow the GRAD shardings — each device owns
        1/n of the moments (the ZeRO memory win); everything else
        (counts, schedule scalars) replicates."""
        repl = NamedSharding(self.mesh, PartitionSpec())
        return jax.tree.map(
            lambda l: self._sharding_for_shape(getattr(l, "shape", ())) or repl,
            abstract_opt,
        )

    def pin_opt_state(self, opt_state: Any) -> Any:
        """Constrain a NEW optimizer state to the same shardings its input
        had, so the donated buffers round-trip stably step over step."""
        return jax.tree.map(
            lambda l: (
                jax.lax.with_sharding_constraint(
                    l, self._sharding_for_shape(l.shape)
                )
                if getattr(l, "ndim", 0) and self._sharding_for_shape(l.shape)
                else l
            ),
            opt_state,
        )

    def fingerprint(self) -> str:
        """Key material for the jit-reuse cache: anything that changes the
        traced collective structure."""
        return (
            f"overlap:on:buckets={len(self.buckets)}:synced={self.synced_leaves}"
            if self.enabled
            else "overlap:off"
        )


def sync_axis_size(mesh: Mesh) -> int:
    n = 1
    for a in SYNC_AXES:
        n *= mesh.shape.get(a, 1)
    return n


def build_plan(
    abstract_params: Any,
    param_shardings: Any,
    mesh: Mesh,
    *,
    enabled: bool,
    bucket_bytes: int = 4 * 1024 * 1024,
    min_sync_bytes: int = _MIN_SYNC_BYTES,
) -> Optional[GradSyncPlan]:
    """Plan the overlapped sync for one param tree; None when the mesh has
    no gradient-reduction axes (nothing to sync — single device or pure
    model parallelism)."""
    n_sync = sync_axis_size(mesh)
    if n_sync <= 1:
        return None

    leaves, treedef = jax.tree.flatten(abstract_params)
    shard_leaves = jax.tree.leaves(param_shardings)
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            "param_shardings tree does not match the param tree "
            f"({len(shard_leaves)} vs {len(leaves)} leaves)"
        )

    import math

    sync_shardings: List[Optional[NamedSharding]] = []
    ring_bytes = 0
    grad_itemsize = 4  # grads reduce in f32
    for aval, psh in zip(leaves, shard_leaves):
        shape = tuple(getattr(aval, "shape", ()))
        nbytes = math.prod(shape) * grad_itemsize
        # ring all-reduce and RS+AG move the same 2*(n-1)/n of the payload
        ring_bytes += int(2 * (n_sync - 1) / n_sync * nbytes)
        spec = None
        if enabled and nbytes >= min_sync_bytes:
            spec = grad_sync_spec(
                shape, getattr(psh, "spec", PartitionSpec()), mesh, SYNC_AXES
            )
        sync_shardings.append(
            NamedSharding(mesh, spec) if spec is not None else None
        )

    # buckets in REVERSE flatten order: backward produces the last-used
    # params' grads first, so reverse order approximates production order
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):
        if sync_shardings[i] is None:
            continue
        shape = tuple(leaves[i].shape)
        nbytes = 1
        for d in shape:
            nbytes *= d
        nbytes *= grad_itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))

    dev = mesh.devices.flat[0]
    comm = CommModel(
        bytes_per_step=ring_bytes,
        n_buckets=len(buckets) if enabled else 1,
        bandwidth=_chip_bw(getattr(dev, "device_kind", "")),
    )
    plan = GradSyncPlan(
        mesh=mesh,
        enabled=enabled,
        treedef=treedef,
        param_shardings=list(shard_leaves),
        sync_shardings=sync_shardings,
        buckets=buckets,
        comm=comm,
        synced_leaves=sum(1 for s in sync_shardings if s is not None),
        _leaf_shapes=[tuple(getattr(l, "shape", ())) for l in leaves],
    )
    return plan
