"""Overlapped gradient synchronization: bucketed reduce-scatter in backward.

Motivation (BASELINE.md r3 roofline, docs/performance.md): with matmul
fusions at 85-88% of peak and the optimizer at its bandwidth roofline, the
remaining step-time lever on the gradient path is *structural* — the
single end-of-backward gradient reduction over the data/fsdp axes sits on
the critical path with nothing left to hide behind.  The Megatron-LM /
ZeRO recipe restructures it: issue the gradient collectives per-bucket as
backward products become available, keep the optimizer consuming SHARDED
gradients and state (reduce-scatter -> sharded update -> all-gather
params), and let the scheduler interleave the collectives with the
remaining backward compute.

The XLA/jax-native expression of that recipe (this module):

- ``build_plan`` partitions the (abstract) grad pytree into size-bounded
  **buckets** in reverse-forward order — the order backward produces them;
- each bucket gets a ``custom_vjp`` identity **marker** applied to the
  params inside the loss: its backward rule pins that bucket's cotangents
  to a sharded layout over the sync axes
  (``parallel/sharding.py:grad_sync_spec``), which XLA lowers to a
  reduce-scatter at the grad's production point.  Each bucket's collective
  is an independent dataflow node (no false dependency on the other
  buckets), which is exactly what XLA's latency-hiding scheduler needs to
  interleave them with backward compute on TPU;
- the optimizer state mirrors the grad shardings (``opt_shardings`` — the
  ZeRO-1/2 memory win: mu/nu live at 1/n per device), and the updated
  params are constrained back to their own shardings, which lowers to the
  closing all-gather.  Total bytes moved equal the baseline all-reduce
  (ring RS + ring AG == ring AR); only the exposure changes;
- deliberately NOT done: concatenating a bucket's leaves into one flat
  payload (the DDP trick).  Under GSPMD the flatten/unflatten of a
  sharded payload inserts extra resharding collectives that cost more
  than the per-leaf launch overhead they save; the bucket here is the
  unit of marker arity and comm accounting, while fusion of adjacent
  small collectives is left to XLA.

Numerics: reduce-scatter + all-gather sums the same shard partials as the
all-reduce, so the step is equivalent up to float reassociation —
``tests/test_step_optimizations.py`` pins params/opt_state allclose after
N steps on the 8-device virtual mesh, and the compiled HLO contains the
expected reduce-scatter structure.

Comm accounting (``CommModel``): the goodput ledger's ``step.comm``
category is fed from an explicit bucket-schedule model — measured payload
bytes over a per-chip interconnect bandwidth, with bucket k's collective
hideable behind the backward compute of buckets k+1..B (baseline: one
bucket, nothing hides).  It is a *model* (labeled as such in the ledger);
the xplane op table stays the ground truth on real chips.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.parallel.mesh import MeshAxes
from determined_tpu.parallel.sharding import grad_sync_spec

#: axes a gradient reduction runs over: every batch-carrying axis
SYNC_AXES = MeshAxes.BATCH_AXES

#: batch axes reachable over ICI (within one slice) — the hierarchical
#: sync reduce-scatters over these and crosses ``dcn`` with the fragment
ICI_SYNC_AXES = MeshAxes.ICI_BATCH_AXES

#: leaves below this ride the final all-reduce: a reduce-scatter of a few
#: KiB is pure launch overhead (norm scales, biases)
_MIN_SYNC_BYTES = 64 * 1024

# Per-chip interconnect bandwidth (bytes/s, one direction) for the comm
# model — public ICI spec-sheet numbers, longest-prefix matched like the
# peak-FLOPs table in observability/_goodput.py.  DTPU_COMM_BW_GBPS
# overrides (and is the only honest choice on CPU test meshes).
ICI_BW_BY_KIND = {
    "TPU v4": 3 * 2 * 50e9,
    "TPU v5 lite": 1 * 2 * 50e9,   # v5e: 1 ICI link pair per chip side
    "TPU v5p": 3 * 2 * 100e9,
    "TPU v5": 3 * 2 * 100e9,
    "TPU v6 lite": 2 * 2 * 90e9,
    "TPU v6e": 2 * 2 * 90e9,
}
_DEFAULT_BW = 10e9  # unknown chip (CPU virtual mesh): placeholder, labeled

# Per-chip cross-slice (DCN) bandwidth: host NIC share per chip.  Order of
# magnitude below ICI — which is the whole point of the hierarchical sync.
DCN_BW_BY_KIND = {
    "TPU v4": 6.25e9,       # ~200 Gb/s host NIC / 4 chips
    "TPU v5 lite": 6.25e9,
    "TPU v5p": 12.5e9,      # ~400 Gb/s host NIC / 4 chips
    "TPU v5": 12.5e9,
    "TPU v6 lite": 12.5e9,
    "TPU v6e": 12.5e9,
}
_DEFAULT_DCN_BW = 1e9  # unknown chip (CPU virtual mesh): placeholder, labeled


def _parse_bw_env(raw: str) -> Dict[str, float]:
    """Parse ``DTPU_COMM_BW_GBPS``: either a single number (every link,
    back-compat) or the per-link form ``ici:90,dcn:12``.  Values are GB/s;
    garbage raises at parse time instead of silently mis-modeling comm."""
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    if not parts:
        raise ValueError("DTPU_COMM_BW_GBPS is set but empty")
    out: Dict[str, float] = {}
    if len(parts) == 1 and ":" not in parts[0]:
        try:
            v = float(parts[0])
        except ValueError:
            raise ValueError(
                f"DTPU_COMM_BW_GBPS={raw!r}: expected a number (GB/s) or "
                "per-link 'ici:90,dcn:12'"
            ) from None
        if v <= 0:
            raise ValueError(f"DTPU_COMM_BW_GBPS={raw!r}: bandwidth must be > 0")
        return {"ici": v * 1e9, "dcn": v * 1e9}
    for part in parts:
        link, sep, val = part.partition(":")
        link = link.strip().lower()
        if not sep or link not in ("ici", "dcn"):
            raise ValueError(
                f"DTPU_COMM_BW_GBPS={raw!r}: bad entry {part!r} "
                "(expected 'ici:<GB/s>' or 'dcn:<GB/s>')"
            )
        if link in out:
            raise ValueError(f"DTPU_COMM_BW_GBPS={raw!r}: duplicate link {link!r}")
        try:
            v = float(val)
        except ValueError:
            raise ValueError(
                f"DTPU_COMM_BW_GBPS={raw!r}: {val!r} is not a number (GB/s)"
            ) from None
        if v <= 0:
            raise ValueError(f"DTPU_COMM_BW_GBPS={raw!r}: bandwidth must be > 0")
        out[link] = v * 1e9
    return out


def _table_bw(device_kind: str, table: Dict[str, float], default: float) -> float:
    for prefix in sorted(table, key=len, reverse=True):
        if device_kind.startswith(prefix):
            return table[prefix]
    return default


def link_bandwidths(device_kind: str) -> Tuple[float, float]:
    """(ici_bw, dcn_bw) in bytes/s for the comm model, env-overridable."""
    env = os.environ.get("DTPU_COMM_BW_GBPS")
    override = _parse_bw_env(env) if env else {}
    ici = override.get("ici") or _table_bw(device_kind, ICI_BW_BY_KIND, _DEFAULT_BW)
    dcn = override.get("dcn") or _table_bw(device_kind, DCN_BW_BY_KIND, _DEFAULT_DCN_BW)
    return ici, dcn


def _chip_bw(device_kind: str) -> float:
    """ICI bandwidth only (back-compat shim for older callers)."""
    return link_bandwidths(device_kind)[0]


@dataclasses.dataclass(frozen=True)
class CommModel:
    """Bucket-schedule exposure model for the ``step.comm`` ledger rows.

    Link-aware since the multi-slice PR: the intra-slice (ICI) and the
    cross-slice (DCN) hop carry different payloads over bandwidths an
    order of magnitude apart, so the ledger models them separately.  A
    single-slice mesh has ``dcn_bytes == 0`` and collapses to the old
    one-hop model.
    """

    bytes_per_step: int      # ICI RS+AG (or AR) payload bytes, ring-counted
    n_buckets: int           # 1 = baseline end-of-backward reduction
    bandwidth: float         # ICI bytes/s
    bwd_frac: float = 0.6    # share of a step that is backward compute
    dcn_bytes_per_step: int = 0   # cross-slice hop payload bytes
    dcn_bandwidth: float = _DEFAULT_DCN_BW

    def split_hops(self, avg_step_s: float) -> Dict[str, Tuple[float, float]]:
        """Per-hop ``{hop: (exposed_s, hidden_s)}`` under the bucket
        schedule.

        Baseline (one bucket): everything is exposed — backward is already
        finished when the reduction runs.  Overlapped (B buckets): bucket
        k's collective can hide behind buckets k+1..B's backward compute,
        so up to (B-1)/B of each hop hides, bounded by the backward time
        actually available.  The DCN hop is issued earliest in backward
        (it is the slowest link with the longest tail to hide behind), so
        it gets first claim on the hiding budget.
        """
        ici_s = self.bytes_per_step / max(self.bandwidth, 1.0)
        dcn_s = self.dcn_bytes_per_step / max(self.dcn_bandwidth, 1.0)
        if self.n_buckets <= 1:
            return {"ici": (ici_s, 0.0), "dcn": (dcn_s, 0.0)}
        frac = (self.n_buckets - 1) / self.n_buckets
        budget = max(avg_step_s, 0.0) * self.bwd_frac
        out: Dict[str, Tuple[float, float]] = {}
        for hop, comm_s in (("dcn", dcn_s), ("ici", ici_s)):
            hidden = min(comm_s * frac, budget)
            budget -= hidden
            out[hop] = (comm_s - hidden, hidden)
        return out

    def split(self, avg_step_s: float) -> Tuple[float, float]:
        """(exposed_s, hidden_s) per step, summed over both hops."""
        hops = self.split_hops(avg_step_s)
        return (
            sum(e for e, _ in hops.values()),
            sum(h for _, h in hops.values()),
        )

    @property
    def total_bytes_per_step(self) -> int:
        return self.bytes_per_step + self.dcn_bytes_per_step


def _make_bucket_marker(shardings: Tuple[Optional[NamedSharding], ...]):
    """custom_vjp identity over one bucket's leaves whose backward pins
    each cotangent to its sync sharding (the reduce-scatter issue point).
    Forward is the identity, so the marker never perturbs the loss."""

    @jax.custom_vjp
    def mark(*leaves):
        return leaves

    def fwd(*leaves):
        return leaves, None

    def bwd(_, cts):
        return tuple(
            ct if s is None else jax.lax.with_sharding_constraint(ct, s)
            for ct, s in zip(cts, shardings)
        )

    mark.defvjp(fwd, bwd)
    return mark


@dataclasses.dataclass
class GradSyncPlan:
    """Everything the train step needs to overlap gradient sync.

    Built once per Trainer setup from the abstract param tree; all methods
    are trace-safe (called inside the jitted step).
    """

    mesh: Mesh
    enabled: bool
    treedef: Any
    param_shardings: List[NamedSharding]          # flat, param order
    sync_shardings: List[Optional[NamedSharding]]  # flat; None = unsynced
    buckets: List[Tuple[int, ...]]                 # leaf indices per bucket
    comm: CommModel
    synced_leaves: int
    # hierarchical two-level sync: grads reduce-scatter over ICI axes only
    # and cross `dcn` as the 1/N_ici fragment (0 = flat treatment)
    hierarchical_dcn: int = 0
    _markers: List[Any] = dataclasses.field(default_factory=list)
    _shape_map: Dict[Tuple[int, ...], NamedSharding] = dataclasses.field(
        default_factory=dict
    )

    _leaf_shapes: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        self._markers = [
            _make_bucket_marker(tuple(self.sync_shardings[i] for i in b))
            for b in self.buckets
        ]
        # shape -> sync sharding, for optimizer-state mirror leaves.  Well
        # defined: the sync spec is a function of (shape, param spec), and
        # same-shape params get the same spec by construction.
        for i, s in enumerate(self.sync_shardings):
            if s is not None:
                self._shape_map.setdefault(self._leaf_shapes[i], s)

    def mark(self, params: Any) -> Any:
        """Apply the bucket markers to the param pytree inside the loss."""
        leaves = jax.tree.leaves(params)
        out = list(leaves)
        for marker, idxs in zip(self._markers, self.buckets):
            marked = marker(*(leaves[i] for i in idxs))
            for j, i in enumerate(idxs):
                out[i] = marked[j]
        return jax.tree.unflatten(self.treedef, out)

    def apply_grad_sync(self, grads: Any) -> Any:
        """Pin an already-accumulated grad tree to the sync shardings —
        the gradient-accumulation path, where the sync must happen ONCE
        per optimizer step on the summed grads, not per microbatch."""
        leaves = list(jax.tree.leaves(grads))
        for i, s in enumerate(self.sync_shardings):
            if s is not None:
                leaves[i] = jax.lax.with_sharding_constraint(leaves[i], s)
        return jax.tree.unflatten(self.treedef, leaves)

    def restore_params(self, new_params: Any) -> Any:
        """Constrain updated params back to their own shardings — the
        closing all-gather of the reduce-scatter/all-gather pair."""
        leaves = list(jax.tree.leaves(new_params))
        for i, s in enumerate(self.param_shardings):
            if self.sync_shardings[i] is not None:
                leaves[i] = jax.lax.with_sharding_constraint(leaves[i], s)
        return jax.tree.unflatten(self.treedef, leaves)

    def _sharding_for_shape(self, shape: Tuple[int, ...]) -> Optional[NamedSharding]:
        return self._shape_map.get(tuple(shape))

    def opt_shardings(self, abstract_opt: Any) -> Any:
        """Sharding tree for the optimizer state: param-shaped mirror
        leaves (adam mu/nu) follow the GRAD shardings — each device owns
        1/n of the moments (the ZeRO memory win); everything else
        (counts, schedule scalars) replicates."""
        repl = NamedSharding(self.mesh, PartitionSpec())
        return jax.tree.map(
            lambda l: self._sharding_for_shape(getattr(l, "shape", ())) or repl,
            abstract_opt,
        )

    def pin_opt_state(self, opt_state: Any) -> Any:
        """Constrain a NEW optimizer state to the same shardings its input
        had, so the donated buffers round-trip stably step over step."""
        return jax.tree.map(
            lambda l: (
                jax.lax.with_sharding_constraint(
                    l, self._sharding_for_shape(l.shape)
                )
                if getattr(l, "ndim", 0) and self._sharding_for_shape(l.shape)
                else l
            ),
            opt_state,
        )

    def fingerprint(self) -> str:
        """Key material for the jit-reuse cache: anything that changes the
        traced collective structure."""
        if not self.enabled:
            return "overlap:off"
        hier = (
            f":hier=dcn{self.hierarchical_dcn}" if self.hierarchical_dcn > 1 else ":flat"
        )
        return (
            f"overlap:on:buckets={len(self.buckets)}:synced={self.synced_leaves}{hier}"
        )


def sync_axis_size(mesh: Mesh) -> int:
    n = 1
    for a in SYNC_AXES:
        n *= mesh.shape.get(a, 1)
    return n


def build_plan(
    abstract_params: Any,
    param_shardings: Any,
    mesh: Mesh,
    *,
    enabled: bool,
    bucket_bytes: int = 4 * 1024 * 1024,
    min_sync_bytes: int = _MIN_SYNC_BYTES,
    hierarchical: bool = False,
) -> Optional[GradSyncPlan]:
    """Plan the overlapped sync for one param tree; None when the mesh has
    no gradient-reduction axes (nothing to sync — single device or pure
    model parallelism).

    ``hierarchical`` (``optimizations.hierarchical_collectives``) switches
    a multi-slice mesh to the two-level scheme: per-bucket reduce-scatter
    over the intra-slice ICI axes only, leaving ``dcn`` replicated — XLA
    then closes the reduction with a cross-slice all-reduce carrying only
    the 1/N_ici sharded fragment, and the param restore all-gathers within
    the slice.  Flat treatment instead shards over every batch axis, which
    rings full-gradient-scale payload across the slow DCN links.
    """
    n_sync = sync_axis_size(mesh)
    if n_sync <= 1:
        return None

    n_dcn = mesh.shape.get(MeshAxes.DCN, 1)
    n_ici = max(1, n_sync // max(1, n_dcn))
    hier = bool(hierarchical) and n_dcn > 1 and n_ici > 1
    sync_axes = ICI_SYNC_AXES if hier else SYNC_AXES

    leaves, treedef = jax.tree.flatten(abstract_params)
    shard_leaves = jax.tree.leaves(param_shardings)
    if len(shard_leaves) != len(leaves):
        raise ValueError(
            "param_shardings tree does not match the param tree "
            f"({len(shard_leaves)} vs {len(leaves)} leaves)"
        )

    import math

    sync_shardings: List[Optional[NamedSharding]] = []
    ici_bytes = 0
    dcn_bytes = 0
    grad_itemsize = 4  # grads reduce in f32
    for aval, psh in zip(leaves, shard_leaves):
        shape = tuple(getattr(aval, "shape", ()))
        nbytes = math.prod(shape) * grad_itemsize
        # per-hop ring accounting: RS+AG within the slice moves
        # 2*(n_ici-1)/n_ici of the payload over ICI; the cross-slice hop
        # rings 2*(n_dcn-1)/n_dcn of the payload over DCN — the FULL
        # payload under flat treatment, only the 1/n_ici fragment under
        # the hierarchical scheme.
        ici_bytes += int(2 * (n_ici - 1) / n_ici * nbytes)
        if n_dcn > 1:
            dcn_payload = nbytes // n_ici if hier else nbytes
            dcn_bytes += int(2 * (n_dcn - 1) / n_dcn * dcn_payload)
        spec = None
        if enabled and nbytes >= min_sync_bytes:
            spec = grad_sync_spec(
                shape, getattr(psh, "spec", PartitionSpec()), mesh, sync_axes
            )
        sync_shardings.append(
            NamedSharding(mesh, spec) if spec is not None else None
        )

    # buckets in REVERSE flatten order: backward produces the last-used
    # params' grads first, so reverse order approximates production order
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):
        if sync_shardings[i] is None:
            continue
        shape = tuple(leaves[i].shape)
        nbytes = 1
        for d in shape:
            nbytes *= d
        nbytes *= grad_itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))

    dev = mesh.devices.flat[0]
    ici_bw, dcn_bw = link_bandwidths(getattr(dev, "device_kind", ""))
    comm = CommModel(
        bytes_per_step=ici_bytes,
        n_buckets=len(buckets) if enabled else 1,
        bandwidth=ici_bw,
        dcn_bytes_per_step=dcn_bytes,
        dcn_bandwidth=dcn_bw,
    )
    plan = GradSyncPlan(
        mesh=mesh,
        enabled=enabled,
        treedef=treedef,
        param_shardings=list(shard_leaves),
        sync_shardings=sync_shardings,
        buckets=buckets,
        comm=comm,
        synced_leaves=sum(1 for s in sync_shardings if s is not None),
        hierarchical_dcn=n_dcn if hier else 0,
        _leaf_shapes=[tuple(getattr(l, "shape", ())) for l in leaves],
    )
    return plan
