"""Quantized matmul arithmetic: int8/fp8 projections with fp32 masters.

Motivation (BASELINE.md r3 roofline, docs/performance.md): the dense
matmul fusions measure 85-88% of the chip's **bf16** peak — micro-tuning
cannot pass a roofline; only changing the arithmetic moves it.  int8 MXU
throughput is ~2x bf16 on every TPU generation and fp8 matches it on
chips that support fp8, so routing the transformer's projection matmuls
through reduced-precision arithmetic is the one lever that raises the
ceiling itself.

Recipe (the Q8BERT / SwitchBack shape, expressed as a flax
``dot_general`` injection so the param tree is untouched):

- **fp32 master weights**: params and optimizer state stay exactly as
  they are (``param_dtype=f32``, Adam moments unchanged) — quantization
  happens per-matmul on the fly, so checkpoints, sharding specs, and the
  fused-AdamW path are byte-compatible with the unquantized model;
- **per-channel dynamic scaling**: both operands are scaled by their
  per-output-channel absmax over the contracting dims (activations
  per-row, weights per-column), quantized to int8 (symmetric, 127) or
  fp8 e4m3 (448), matmul'd with an int32/f32 accumulator, and rescaled;
- **straight-through backward**: the custom_vjp backward transposes the
  REFERENCE matmul via ``jax.linear_transpose`` on the full-precision
  residuals — gradients never see quantization noise (the standard
  stability recipe; forward noise alone keeps the loss within tolerance
  of the bf16 oracle, pinned by tests/test_step_optimizations.py).

Platform gate: int8 ``dot_general`` lowers everywhere (TPU MXU native,
CPU via XLA).  fp8 needs hardware support (TPU v5p/v6+, Hopper-class
GPUs) — requesting it elsewhere raises ``InvalidExperimentConfig`` at
setup, except under ``DTPU_QUANT_EMULATE=1`` which permits the (slow,
numerics-only) emulated path for tests.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from determined_tpu.config.experiment import QUANT_MODES, InvalidExperimentConfig

#: TPU generations with native fp8 matmul support (prefix match on
#: device_kind, same convention as the peak-FLOPs table)
_FP8_TPU_PREFIXES = ("TPU v5p", "TPU v6", "TPU v7")
_FP8_GPU_MARKERS = ("H100", "H200", "B100", "B200", "GH200")


def fp8_supported(
    backend: Optional[str] = None, device_kind: Optional[str] = None
) -> bool:
    if os.environ.get("DTPU_QUANT_EMULATE", "0") == "1":
        return True
    backend = backend or jax.default_backend()
    if device_kind is None:
        devs = jax.devices()
        device_kind = getattr(devs[0], "device_kind", "") if devs else ""
    if backend == "tpu":
        return any(device_kind.startswith(p) for p in _FP8_TPU_PREFIXES)
    if backend == "gpu":
        return any(m in device_kind for m in _FP8_GPU_MARKERS)
    return False


def require_platform(
    mode: str, backend: Optional[str] = None, device_kind: Optional[str] = None
) -> None:
    """Raise ``InvalidExperimentConfig`` when the requested quantized
    matmul mode cannot run on this platform (clear message, at setup time
    — not a cryptic lowering error mid-compile)."""
    if mode not in QUANT_MODES:
        raise InvalidExperimentConfig(
            f"quantized_matmul {mode!r} not in {QUANT_MODES}"
        )
    if mode != "fp8":
        return
    backend = backend or jax.default_backend()
    if not fp8_supported(backend, device_kind):
        devs = jax.devices()
        kind = device_kind or (getattr(devs[0], "device_kind", "") if devs else "")
        raise InvalidExperimentConfig(
            f"quantized_matmul: fp8 is not supported on this platform "
            f"(backend={backend!r}, device_kind={kind!r}); fp8 needs "
            f"TPU v5p/v6+ or a Hopper-class GPU — use int8 here, or set "
            f"DTPU_QUANT_EMULATE=1 for the slow emulated path in tests"
        )


def _contract_scale(x: jax.Array, contract_dims: Tuple[int, ...], qmax: float):
    """Per-channel symmetric scale: absmax over the contracting dims."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=contract_dims, keepdims=True)
    return jnp.maximum(amax, 1e-12) / qmax


def _squeeze_to_out(
    scale: jax.Array, contract_dims: Tuple[int, ...], lead: int, trail: int
) -> jax.Array:
    """Reshape a keepdims per-channel scale to broadcast over the
    dot_general output: drop contract dims, pad ``lead``/``trail`` size-1
    dims for the other operand's free dims."""
    s = lax.squeeze(scale, contract_dims)
    return s.reshape((1,) * lead + s.shape + (1,) * trail)


@functools.lru_cache(maxsize=256)
def _quant_dot(mode: str, dn: Any) -> Any:
    """custom_vjp quantized dot for one (mode, dimension_numbers).

    Cached so repeated flax layer calls share one primitive-like callable
    per signature (keeps trace size and custom_vjp count bounded).
    """
    (c_l, c_r), (b_l, b_r) = dn
    if b_l or b_r:  # flax Dense/DenseGeneral never uses batch dims
        raise NotImplementedError(
            "quantized dot_general does not support batch dimensions"
        )
    c_l, c_r = tuple(c_l), tuple(c_r)

    def quantized(lhs: jax.Array, rhs: jax.Array) -> jax.Array:
        out_dtype = lhs.dtype
        if mode == "int8":
            s_l = _contract_scale(lhs, c_l, 127.0)
            s_r = _contract_scale(rhs, c_r, 127.0)
            q_l = jnp.clip(jnp.round(lhs / s_l), -127, 127).astype(jnp.int8)
            q_r = jnp.clip(jnp.round(rhs / s_r), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(
                q_l, q_r, dn, preferred_element_type=jnp.int32
            ).astype(jnp.float32)
        else:  # fp8 (e4m3 values; accumulation in f32)
            f8 = jnp.float8_e4m3fn
            s_l = _contract_scale(lhs, c_l, 448.0)
            s_r = _contract_scale(rhs, c_r, 448.0)
            q_l = (lhs / s_l).astype(f8)
            q_r = (rhs / s_r).astype(f8)
            acc = lax.dot_general(
                q_l, q_r, dn, preferred_element_type=jnp.float32
            )
        n_free_l = lhs.ndim - len(c_l)
        n_free_r = rhs.ndim - len(c_r)
        out = (
            acc
            * _squeeze_to_out(s_l, c_l, 0, n_free_r)
            * _squeeze_to_out(s_r, c_r, n_free_l, 0)
        )
        return out.astype(out_dtype)

    @jax.custom_vjp
    def qdot(lhs, rhs):
        return quantized(lhs, rhs)

    def fwd(lhs, rhs):
        return quantized(lhs, rhs), (lhs, rhs)

    def bwd(res, g):
        lhs, rhs = res
        # straight-through: transpose the REFERENCE (unquantized) matmul,
        # so gradients are exact for the full-precision linearization
        g = g.astype(lhs.dtype)
        d_lhs = jax.linear_transpose(
            lambda a: lax.dot_general(a, rhs, dn), lhs
        )(g)[0]
        d_rhs = jax.linear_transpose(
            lambda b: lax.dot_general(lhs, b, dn), rhs
        )(g)[0]
        return d_lhs, d_rhs

    qdot.defvjp(fwd, bwd)
    return qdot


def _canon_dn(dimension_numbers: Any) -> Any:
    (c_l, c_r), (b_l, b_r) = dimension_numbers
    return (tuple(c_l), tuple(c_r)), (tuple(b_l), tuple(b_r))


def make_dot_general(mode: str) -> Any:
    """A ``lax.dot_general``-compatible callable routing through the
    quantized path — inject into flax ``Dense``/``DenseGeneral`` via
    their ``dot_general=`` attribute, so the param tree, initializers,
    and partitioning metadata are untouched."""
    if mode in (None, "none"):
        return lax.dot_general

    def dot_general(
        lhs: jax.Array,
        rhs: jax.Array,
        dimension_numbers: Any,
        precision: Any = None,
        preferred_element_type: Any = None,
    ) -> jax.Array:
        del precision, preferred_element_type  # quantized path fixes both
        return _quant_dot(mode, _canon_dn(dimension_numbers))(lhs, rhs)

    return dot_general
