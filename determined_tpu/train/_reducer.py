"""Validation-metric reducers: how per-batch metrics combine across the
validation sweep.

Reference: ``harness/determined/pytorch/_reducer.py`` (518 LoC) — there,
reduction happens across *slots* via distributed gather.  TPU-first
redesign: every per-batch metric is already a global scalar (computed from
mesh-global arrays inside the jitted eval step), so cross-chip reduction is
XLA's job; what the user controls is the across-batch combine.  A reducer
is a (init, accumulate, finalize) triple that runs inside the jitted eval
step, so custom reducers cost no extra host syncs.

Built-ins match the reference's ``pytorch.Reducer`` enum: AVG/SUM/MIN/MAX
(+ LAST).  Custom reducers subclass nothing — construct ``MetricReducer``
with jit-able callables.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Union

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MetricReducer:
    """across-batch combine for one validation metric.

    ``accumulate(carry, value) -> carry`` runs inside the jitted eval step
    per batch; ``finalize(carry, n_batches) -> value`` runs once, host-side.
    """

    init: float
    accumulate: Callable[[jax.Array, jax.Array], jax.Array]
    finalize: Callable[[float, float], float] = lambda carry, n: carry


MEAN = MetricReducer(
    init=0.0,
    accumulate=lambda c, v: c + v,
    finalize=lambda c, n: c / max(n, 1.0),
)
SUM = MetricReducer(init=0.0, accumulate=lambda c, v: c + v)
MIN = MetricReducer(init=float("inf"), accumulate=jnp.minimum)
MAX = MetricReducer(init=float("-inf"), accumulate=jnp.maximum)
LAST = MetricReducer(init=0.0, accumulate=lambda c, v: v)

_BUILTINS: Dict[str, MetricReducer] = {
    "mean": MEAN,
    "avg": MEAN,
    "sum": SUM,
    "min": MIN,
    "max": MAX,
    "last": LAST,
}

ReducerSpec = Union[str, MetricReducer]


def get_reducer(spec: ReducerSpec) -> MetricReducer:
    if isinstance(spec, MetricReducer):
        return spec
    try:
        return _BUILTINS[str(spec).lower()]
    except KeyError:
        raise ValueError(
            f"unknown reducer {spec!r}; builtins: {sorted(_BUILTINS)}"
        ) from None
