"""TrainState: the complete on-device training state pytree.

Design note (TPU-first): the reference fetches per-batch metrics to host
inside the hot loop (``_pytorch_trial.py:716`` ``metric.cpu()``) — that
pattern stalls the XLA pipeline.  Here metric accumulation lives INSIDE the
jitted step as part of the state (``metric_acc``/``metric_count``): running
sums ride along on device and are fetched only at report boundaries.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    """Everything the jitted train step reads and writes.

    step:          global optimizer step counter (device scalar).
    params:        model parameters (possibly sharded).
    opt_state:     optax optimizer state (sharded like params).
    rng:           base PRNG key; per-step keys are folded from it.
    metric_acc:    running per-metric sums since the last report boundary.
    metric_count:  number of accumulated steps.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    metric_acc: Dict[str, jax.Array]
    metric_count: jax.Array

    @classmethod
    def create(
        cls,
        params: Any,
        opt_state: Any,
        rng: jax.Array,
        metric_keys: tuple,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=rng,
            metric_acc={k: jnp.zeros((), jnp.float32) for k in metric_keys},
            metric_count=jnp.zeros((), jnp.float32),
        )

    def reset_metrics(self) -> "TrainState":
        return self.replace(
            metric_acc={k: jnp.zeros((), jnp.float32) for k in self.metric_acc},
            metric_count=jnp.zeros((), jnp.float32),
        )

    def fetch_metrics(self) -> Dict[str, float]:
        """One host sync: mean of each accumulated metric."""
        acc, count = jax.device_get((self.metric_acc, self.metric_count))
        if count == 0:
            return {}
        return {k: float(v) / float(count) for k, v in acc.items()}
