"""flax >= 0.10 compatibility: logical-name Partitioned boxes under a mesh.

flax 0.10 made ``Partitioned.unbox`` apply the box's axis names as a
``with_sharding_constraint`` whenever an ambient mesh is active — and the
init-fn shape check in ``Scope.param`` unboxes during ``apply`` too, so the
constraint fires on every traced step, not just at init.  This repo boxes
params with LOGICAL names (embed/heads/kv/...) and the Trainer maps them to
mesh axes itself (``param_logical_specs`` -> ``param_shardings`` through the
context's logical-axis rules); flax's eager constraint then hands jax a
PartitionSpec of names that are not mesh axes and every apply under
``with mesh:`` dies with "Resource axis ... not found in mesh".

The patch skips the constraint exactly when its names cannot resolve in the
active mesh — boxes that DO name real mesh axes keep flax's behavior.
Installed once from ``determined_tpu.train`` import.
"""

from __future__ import annotations

from typing import Any

import jax
from flax.core import meta as _meta

_orig_unbox = _meta.Partitioned.unbox


def _active_mesh(box: Any):
    if box.mesh is not None:
        return box.mesh
    try:
        from jax.interpreters import pxla

        env_mesh = pxla.thread_resources.env.physical_mesh
        return env_mesh if env_mesh.devices.shape != () else None
    except Exception:  # noqa: BLE001 - jax internals moved; behave unpatched
        return None


def _unbox(self, apply_constraint: bool = True):
    if apply_constraint:
        mesh = _active_mesh(self)
        if mesh is not None:
            names = {
                n
                for n in jax.tree_util.tree_leaves(tuple(self.names))
                if isinstance(n, str)
            }
            if not names <= set(str(a) for a in mesh.axis_names):
                # logical (non-mesh) names: placement is the harness's job
                return self.value
    return _orig_unbox(self, apply_constraint=apply_constraint)


def install() -> None:
    """Idempotently patch ``Partitioned.unbox``."""
    if _meta.Partitioned.unbox is not _unbox:
        _meta.Partitioned.unbox = _unbox
