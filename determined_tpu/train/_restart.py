"""Supervised restart-from-checkpoint: the harness-side half of the
reference master's allocation restart policy.

Reference: the master restarts a failed trial allocation from its latest
checkpoint up to ``max_restarts`` times (``master/internal/trial.go``
restart accounting; PAPER.md fault tolerance).  On a TPU VM the process
that failed and the process that supervises are the same host, so the
restart loop lives here: classify the failure (``utils/errors.py``
taxonomy), back off exponentially with jitter, and re-enter
``Trainer.fit(latest_checkpoint=...)`` from the last checkpoint whose
integrity manifest verified.

Split of responsibilities:
- this module: policy + the generic retry loop (``run_with_restarts``),
  usable from tests with any attempt callable;
- ``exec/run_trial.py TrialSupervisor``: binds the loop to a real trial
  process (trainer factory, metrics reporting, cluster env).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable, Dict, Optional

from determined_tpu.observability import get_tracer
from determined_tpu.utils.errors import (
    FailureKind,
    RestartBudgetExhaustedError,
    classify_failure,
)

logger = logging.getLogger("determined_tpu.train.restart")


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """How many restarts, and how fast — from the experiment config
    (``max_restarts`` + the ``fault_tolerance`` section)."""

    max_restarts: int = 5
    backoff_base: float = 1.0
    backoff_cap: float = 60.0
    jitter: float = 0.25

    @classmethod
    def from_exp_config(cls, exp_config: Optional[Any]) -> "RestartPolicy":
        if exp_config is None:
            return cls()
        ft = getattr(exp_config, "fault_tolerance", None)
        if ft is None:
            return cls(max_restarts=exp_config.max_restarts)
        return cls(
            max_restarts=exp_config.max_restarts,
            backoff_base=ft.restart_backoff_base,
            backoff_cap=ft.restart_backoff_cap,
            jitter=ft.restart_backoff_jitter,
        )

    def delay(self, restarts: int, rng: Optional[random.Random] = None) -> float:
        """Exponential backoff with jitter: base * 2^n, capped, +/- jitter.
        ``restarts`` is the number of restarts already taken (0 before the
        first)."""
        raw = min(self.backoff_base * (2 ** restarts), self.backoff_cap)
        if self.jitter and raw > 0:
            r = rng or random
            raw *= 1 + r.uniform(-self.jitter, self.jitter)
        return max(raw, 0.0)


@dataclasses.dataclass
class Attempt:
    """What the supervisor learned from one failed attempt."""

    restarts: int                       # restarts taken so far (incl. this one)
    kind: FailureKind
    exc: BaseException
    latest_checkpoint: Optional[str]    # resume point for the next attempt
    delay: float                        # backoff the supervisor will sleep


def run_with_restarts(
    attempt: Callable[[Optional[str]], Dict[str, Any]],
    *,
    policy: RestartPolicy,
    initial_checkpoint: Optional[str] = None,
    get_latest_checkpoint: Optional[Callable[[], Optional[str]]] = None,
    on_failure: Optional[Callable[[Attempt], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Dict[str, Any]:
    """Run ``attempt(latest_checkpoint)`` until it returns, restarting on
    TRANSIENT failures up to ``policy.max_restarts`` times.

    - ``attempt`` returns a fit-style summary dict on success (including a
      clean preemption exit, which is not a failure).
    - ``get_latest_checkpoint`` is polled after every failure to learn the
      newest durable checkpoint the dead attempt left behind (e.g.
      ``trainer.latest_checkpoint`` — finalized saves only; an async save
      that never drained does not count and cannot poison the resume).
    - ``on_failure`` observes every classified failure (metrics/logging).

    PREEMPTED failures return a synthetic ``stopped_early`` summary — the
    scheduler owns re-placement, not this loop.  FATAL failures re-raise.
    Budget exhaustion raises ``RestartBudgetExhaustedError`` (itself
    FATAL) chained to the last transient failure.
    """
    restarts = 0
    latest = initial_checkpoint
    while True:
        try:
            summary = attempt(latest)
            summary.setdefault("restarts", restarts)
            return summary
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 - classified below
            if get_latest_checkpoint is not None:
                latest = get_latest_checkpoint() or latest
            kind = classify_failure(e)
            if kind == FailureKind.PREEMPTED:
                logger.info("trial preempted (%s); exiting for rescheduling", e)
                if on_failure is not None:
                    on_failure(Attempt(restarts, kind, e, latest, 0.0))
                return {
                    "stopped_early": True,
                    "restarts": restarts,
                    "latest_checkpoint": latest,
                    "preempted": True,
                }
            if kind == FailureKind.FATAL:
                logger.error("fatal trial failure (no restart): %r", e)
                if on_failure is not None:
                    on_failure(Attempt(restarts, kind, e, latest, 0.0))
                raise
            if restarts >= policy.max_restarts:
                exhausted = RestartBudgetExhaustedError(
                    f"trial failed {restarts + 1} times "
                    f"(max_restarts={policy.max_restarts}); last error: {e!r}"
                )
                if on_failure is not None:
                    on_failure(Attempt(restarts, FailureKind.FATAL, exhausted, latest, 0.0))
                raise exhausted from e
            delay = policy.delay(restarts)
            restarts += 1
            logger.warning(
                "transient trial failure (restart %d/%d in %.1fs, resume=%s): %r",
                restarts,
                policy.max_restarts,
                delay,
                latest or "<from scratch>",
                e,
            )
            if on_failure is not None:
                on_failure(Attempt(restarts, kind, e, latest, delay))
            # supervisor spans: the failure marker + the backoff sleep are
            # restart-recovery time in the goodput ledger (the re-setup and
            # checkpoint-restore of the next attempt land in their own
            # setup/restore buckets)
            tracer = get_tracer()
            tracer.instant(
                "trial.failure", "restart", kind=kind.value, restarts=restarts
            )
            if delay > 0:
                with tracer.span("restart.backoff", cat="restart", restarts=restarts):
                    sleep(delay)
