"""Cross-trial jit-reuse cache: share compiled train/eval steps between trials.

A hyperparameter search runs many trials of the SAME architecture; each one
builds a fresh ``Trainer`` whose jitted step closures are new Python
objects, so jax's in-process jit cache misses and every trial re-traces and
re-compiles an identical program.  The reference platform never pays this
because its trials are separate processes that each pay the compile anyway;
here trials share one process (``experiment/scheduler.py`` packs them onto
submeshes), so the compile is shareable work.

This cache closes the gap: jitted ``train_step``/``eval_step`` callables are
keyed on everything that shapes the traced computation —

- the trial class (its ``loss``/``evaluate_batch``/optimizer construction),
- the trial-static hyperparameters (a closure bakes python scalars like a
  learning rate into the HLO as constants, so by default EVERY hparam is
  part of the key; a trial that routes an hparam through runtime state —
  e.g. ``optax.inject_hyperparams`` — may exclude it via
  ``JaxTrial.compile_cache_runtime_hparams``),
- the mesh — axis names, sizes, AND device ids.  Device identity must be
  part of the key because a trial's model may bake its concrete mesh into
  the trace (``with_sharding_constraint``/``shard_map`` over
  ``context.mesh``, as the transformer LM does): a callable compiled
  against gang A's devices cannot serve a trial on gang B.  The scheduler's
  LIFO slot reuse (``SlotPool``) makes this cheap in practice — a stopped
  trial's block is preferentially handed to the next same-architecture
  create, which then hits: same callable, same devices, zero retrace AND
  zero recompile.  Different-gang trials of one architecture each compile
  once; the persistent XLA compilation cache
  (``utils/compilation_cache.py``) covers the cross-process half,
- the host batch structure (shapes/dtypes) and the gradient-accumulation
  settings that change the stacked batch layout.

Two trials that hash to the same key therefore trace to byte-identical HLO
on the same device set, and sharing the callable is sound for any trial,
including ones that close over their concrete mesh.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from determined_tpu.observability import get_tracer

logger = logging.getLogger("determined_tpu.train.jit_cache")


def timed_first_call(fn: Any, label: str) -> Any:
    """Wrap a jitted callable so its FIRST invocation — the one that pays
    trace + compile — is recorded as a ``compile`` span and a
    ``jit_cache.compile_s`` counter.  Every later call pays one list
    index.  A cache-hit trial shares the wrapper, so its first step is
    correctly NOT marked as compile time."""
    done = [False]

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if done[0]:
            return fn(*args, **kwargs)
        done[0] = True  # benign race: two concurrent first-callers both record
        t0 = time.monotonic()
        try:
            return fn(*args, **kwargs)
        finally:
            t1 = time.monotonic()
            tracer = get_tracer()
            tracer.record_span(label, "compile", t0, t1)
            tracer.counter("jit_cache.compile_s", t1 - t0)

    return wrapped


@dataclasses.dataclass
class CachedSteps:
    """One cache entry: the shared jitted callables for a step signature."""

    train_step: Any
    eval_step: Any
    trial_class: str
    hits: int = 0
    # the UNwrapped jax.jit object for train_step: tests and benches use
    # it to lower/inspect the compiled HLO (collective structure) without
    # tripping the first-call compile-span wrapper
    train_jit: Any = None


class StepCache:
    """Bounded, thread-safe LRU of jitted step callables.

    Entries keep their defining trial's closure alive (model/optimizer
    objects), so the cache is bounded: ``maxsize`` distinct step signatures,
    oldest evicted first.  All methods are safe to call from concurrent
    trial threads.
    """

    def __init__(self, maxsize: int = 32) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, CachedSteps]" = OrderedDict()
        self._maxsize = maxsize
        self.hits = 0
        self.misses = 0

    def lookup(self, key: str) -> Optional[CachedSteps]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                entry.hits += 1
        # lock-free tracer push, outside the cache lock on principle
        get_tracer().counter("jit_cache.miss" if entry is None else "jit_cache.hit")
        return entry

    def insert(self, key: str, entry: CachedSteps) -> CachedSteps:
        """Insert, returning the winning entry.  Under a concurrent race the
        first writer wins so every racer converges on ONE callable (later
        same-key trials then share its jax-side trace/executable caches)."""
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                return existing
            self._entries[key] = entry
            while len(self._entries) > self._maxsize:
                evicted_key, _ = self._entries.popitem(last=False)
                logger.debug("jit-reuse cache evicted %s", evicted_key[:12])
            return entry

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
            }


# Process-global instance: trials in one process (the concurrent scheduler,
# sequential searches, tests) all share it.
_cache = StepCache()


def get_step_cache() -> StepCache:
    return _cache


def step_cache_stats() -> Dict[str, int]:
    """Snapshot of the process-global cache counters (bench/observability)."""
    return _cache.stats()


def clear_step_cache() -> None:
    _cache.clear()


def _canonical(value: Any) -> Any:
    """JSON-stable view of an hparam value (Const wrappers collapse)."""
    value = getattr(value, "val", value)
    if isinstance(value, dict):
        # sort on str(k): mixed-type keys (legal YAML) must hash, not raise
        return {
            str(k): _canonical(v)
            for k, v in sorted(value.items(), key=lambda kv: str(kv[0]))
        }
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    return value if isinstance(value, (int, float, str, bool, type(None))) else repr(value)


def step_cache_key(
    *,
    trial: Any,
    hparams: Dict[str, Any],
    mesh: Any,
    agg: int,
    average_grads: bool,
    sample_batch: Dict[str, Any],
    metric_keys: Tuple[str, ...],
    rules: Optional[Dict[str, Any]] = None,
    overlap: str = "overlap:none",
    quant: str = "none",
    pipeline: str = "pipe:none",
) -> str:
    """Hash of everything that shapes the traced train/eval step.

    ``sample_batch`` is the HOST batch (pre-sharding, pre-agg-stacking);
    together with ``agg`` and the mesh axis sizes it determines the traced
    batch avals.  The mesh's device ids are included (see module doc:
    models may bake the concrete mesh into the trace).
    """
    runtime = frozenset(getattr(trial, "compile_cache_runtime_hparams", tuple)() or ())
    static_hp = {k: _canonical(v) for k, v in hparams.items() if k not in runtime}
    payload = {
        "trial": f"{type(trial).__module__}:{type(trial).__qualname__}",
        "hparams": static_hp,
        "mesh": [[name, int(size)] for name, size in mesh.shape.items()],
        "devices": [int(getattr(d, "id", -1)) for d in mesh.devices.flat],
        # logical-axis sharding rules enter the trace (models pass
        # context.rules into sharding constraints), so they key the cache
        "rules": {str(k): _canonical(v) for k, v in (rules or {}).items()},
        "agg": int(agg),
        "average_grads": bool(average_grads),
        # step-program knobs (ISSUE 12/14): the overlapped-grad-sync
        # bucket structure, the quantized-matmul mode, and the pipeline
        # microbatch schedule (name/P/M/virtual stages) all change the
        # traced program without touching hparams or batch avals
        "overlap": str(overlap),
        "quant": str(quant),
        "pipeline": str(pipeline),
        "batch": sorted(
            (k, tuple(int(d) for d in v.shape), str(v.dtype))
            for k, v in sample_batch.items()
        ),
        "metric_keys": list(metric_keys),
    }
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()
