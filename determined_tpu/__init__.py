"""determined_tpu: a TPU-native deep-learning training platform.

A ground-up rebuild of the capabilities of the Determined AI platform
(reference: sirredbeard/determined @ 2024-11-08) designed TPU-first:

- Compute is JAX/XLA: training steps are ``jit``-compiled over a
  ``jax.sharding.Mesh`` with data/fsdp/tensor/sequence/expert/pipeline axes
  (subsuming the reference's DDP/Horovod/DeepSpeed/MPU zoo,
  reference ``harness/determined/pytorch/``).
- The Core API (``determined_tpu.core``) mirrors the reference's
  ``harness/determined/core/`` contexts (distributed, checkpoint, train,
  preempt, profiler, metrics) with a dummy/real split so everything runs
  locally with zero services.
- Hyperparameter search (``determined_tpu.searcher``) re-implements the
  event-driven SearchMethod family from ``master/pkg/searcher/``.

Public surface is re-exported here for ergonomic access.
"""

__version__ = "0.1.0"

from determined_tpu.utils.errors import (  # noqa: F401
    DeterminedTPUError,
    InvalidConfigError,
    CheckpointNotFoundError,
    PreemptedError,
)
