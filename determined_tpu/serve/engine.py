"""The serving engine: jitted prefill/decode kernels + the batching loops.

Two engines share one set of compiled kernels:

- :class:`ServeEngine` — iteration-level **continuous batching**: every
  decode step, finished sequences retire (blocks freed, response
  completed) and queued requests join the freed lanes immediately.  This
  is the production path ``dtpu serve`` runs.
- :class:`StaticBatchEngine` — the naive baseline the A/B in
  ``scripts/bench_serve.py`` measures against: a batch is formed, decoded
  until EVERY member finishes, and only then replaced.  Short requests
  idle their lane while the longest member runs.

Both jitted steps are shaped entirely by :class:`ServeConfig` (lane count,
prompt padding, block-table width), so a mixed stream of request lengths
compiles exactly once per kernel — enforced by wrapping the pre-jit
callables in the PR-4 RetraceSentinel (``lint/_runtime.py``), the same
compile-count guard the Trainer runs under.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from determined_tpu.lint._runtime import get_retrace_sentinel
from determined_tpu.observability import get_tracer
from determined_tpu.serve.config import ServeConfig
from determined_tpu.serve.kv_cache import (
    BlockAllocator,
    CacheOOM,
    prefix_block_hashes,
)
from determined_tpu.serve.scheduler import (
    ActiveSeq,
    AdmissionQueue,
    AdmissionRejected,
    GenRequest,
    LaneTable,
)

logger = logging.getLogger("determined_tpu.serve")


def sample_token(logits: np.ndarray, temperature: float, rng: Any) -> int:
    """Sample one token from f32 logits [vocab]: greedy at temperature 0,
    softmax sampling otherwise.  Shared by the serving engines and the
    full-forward oracle in the parity tests, so 'sampling matches' reduces
    to 'logits match'."""
    if temperature <= 0.0:
        return int(np.argmax(logits))
    z = logits.astype(np.float64) / float(temperature)
    z -= z.max()
    p = np.exp(z)
    total = p.sum()
    if not np.isfinite(total) or total <= 0.0:
        # NaN/inf logits (a numerically degenerate model) must degrade to
        # a bad TOKEN, not a ValueError that kills the scheduler loop
        return int(np.argmax(np.nan_to_num(logits, nan=-np.inf)))
    return int(rng.choice(len(p), p=p / total))


class DecodeKernels:
    """Compiled prefill/decode for one (model cfg, params) pair.

    ``prefill`` runs one request at a time ([1, max_prompt_len] — padded,
    single trace); ``decode`` steps all ``max_batch`` lanes at once.  The
    cache argument is donated: each step writes into the buffers of the
    previous one instead of copying the pool.
    """

    def __init__(self, model_cfg: Any, params: Any, serve_cfg: ServeConfig) -> None:
        import jax

        from determined_tpu.models.transformer import (
            _check_decodable,
            init_kv_cache,
            transformer_decode,
            transformer_prefill,
            transformer_prefill_suffix,
        )

        _check_decodable(model_cfg)
        if "params" in params:  # accept the full TrainState tree or its inner dict
            params = params["params"]
        self.model_cfg = model_cfg
        self.serve_cfg = serve_cfg
        self.params = jax.device_put(params)
        self.cache = init_kv_cache(
            model_cfg, serve_cfg.num_blocks, serve_cfg.block_size
        )
        #: suffix-prefill token width: the prompt padded up to whole blocks
        #: so the chunked walk slices full blocks only (one trace)
        self._suffix_pad = (
            serve_cfg.blocks_for(serve_cfg.max_prompt_len) * serve_cfg.block_size
        )
        sentinel = get_retrace_sentinel()
        prefill = sentinel.wrap(
            "serve.prefill_step",
            functools.partial(transformer_prefill, model_cfg),
            allowed=1,
        )
        # the prefix-cache admission path: cold requests run it with
        # start=0, warm requests from their first un-cached block; either
        # way it is the SAME trace (dynamic trip count inside the kernel)
        prefill_suffix = sentinel.wrap(
            "serve.prefill_suffix_step",
            functools.partial(transformer_prefill_suffix, model_cfg),
            allowed=1,
        )
        decode = sentinel.wrap(
            "serve.decode_step",
            functools.partial(
                transformer_decode,
                model_cfg,
                chunk_blocks=serve_cfg.decode_chunk_blocks,
            ),
            allowed=1,
        )
        self._prefill = jax.jit(prefill, donate_argnums=(4,))
        self._prefill_suffix = jax.jit(prefill_suffix, donate_argnums=(5,))
        self._decode = jax.jit(decode, donate_argnums=(4,))

    # -- kernel entry points (device round trips happen HERE) ---------------

    def prefill(self, prompt: List[int], block_table: List[int]) -> np.ndarray:
        """Run the padded prefill for one sequence, writing its K/V into
        the paged cache; returns the f32 logits at the last prompt token."""
        cfg = self.serve_cfg
        tokens = np.zeros((1, cfg.max_prompt_len), np.int32)
        tokens[0, : len(prompt)] = prompt
        table = np.asarray(block_table, np.int32)[None, :]
        lens = np.asarray([len(prompt)], np.int32)
        logits, self.cache = self._prefill(
            self.params, tokens, lens, table, self.cache
        )
        return np.asarray(logits[0, len(prompt) - 1])

    def prefill_suffix(
        self, prompt: List[int], block_table: List[int], start: int
    ) -> np.ndarray:
        """Prefill only ``prompt[start:]`` (the un-cached suffix; ``start``
        is block-aligned — the cached prefix already sits in the mapped
        blocks).  Returns the f32 logits at the last prompt token."""
        tokens = np.zeros((1, self._suffix_pad), np.int32)
        tokens[0, : len(prompt)] = prompt
        table = np.asarray(block_table, np.int32)[None, :]
        starts = np.asarray([start], np.int32)
        lens = np.asarray([len(prompt)], np.int32)
        logits, self.cache = self._prefill_suffix(
            self.params, tokens, starts, lens, table, self.cache
        )
        return np.asarray(logits[0])

    def decode(
        self, tokens: np.ndarray, positions: np.ndarray, tables: np.ndarray
    ) -> np.ndarray:
        """One decode step over every lane; returns f32 logits [B, vocab]."""
        logits, self.cache = self._decode(
            self.params, tokens, positions, tables, self.cache
        )
        return np.asarray(logits)


class _EngineBase:
    """Admission, sampling, stats, and lifecycle shared by both engines."""

    def __init__(self, kernels: DecodeKernels, thread_name: str) -> None:
        self.kernels = kernels
        self.cfg = kernels.serve_cfg
        self.allocator = BlockAllocator(
            self.cfg.num_blocks,
            self.cfg.block_size,
            prefix_cache=self.cfg.prefix_cache,
        )
        self.queue = AdmissionQueue(self.cfg.queue_depth)
        self._tracer = get_tracer()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._finished = threading.Event()
        #: set when the loop died on an unexpected exception; /healthz
        #: reports it so a crashed engine never keeps serving 'ok'
        self.failed: Optional[str] = None
        self._thread = threading.Thread(
            target=self._run_guarded, name=thread_name, daemon=True
        )
        self._stats_lock = threading.Lock()
        self._submitted = 0
        self._completed = 0
        self._rejected = 0
        self._tokens_generated = 0
        #: requests that finished with an error (prefill crash, engine
        #: stop/crash, drain abandonment) — the error-rate numerator the
        #: master's canary bake compares against its pre-roll baseline
        self._errored = 0
        #: 5xx responses counted by the HTTP layer (note_http_response);
        #: catches handler-level failures the engine never sees
        self._http_5xx = 0
        self._latency_ms_total = 0.0
        self._started_at = time.monotonic()

    # -- admission (HTTP threads) -------------------------------------------

    def submit(
        self,
        prompt: List[int],
        *,
        max_new_tokens: Optional[int] = None,
        temperature: float = 0.0,
        seed: Optional[int] = None,
        stop_token: Optional[int] = None,
    ) -> GenRequest:
        """Admit one request or raise :class:`AdmissionRejected` — 413 for
        requests no drained replica could ever serve, 429 under queue
        backpressure, 503 while draining."""
        with self._tracer.span("serve.admit", cat="serve"):
            if not prompt:
                raise AdmissionRejected(400, "empty prompt")
            if len(prompt) > self.cfg.max_prompt_len:
                raise AdmissionRejected(
                    413,
                    f"prompt of {len(prompt)} tokens exceeds max_prompt_len="
                    f"{self.cfg.max_prompt_len}",
                )
            new = (
                self.cfg.max_new_tokens
                if max_new_tokens is None
                else min(int(max_new_tokens), self.cfg.max_new_tokens)
            )
            if new < 1:  # 0 is a client error, not "use the default"
                raise AdmissionRejected(400, "max_new_tokens must be >= 1")
            if self.allocator.blocks_for(len(prompt) + new) > self.allocator.capacity:
                # permanent: this request can NEVER fit this replica's cache
                raise AdmissionRejected(
                    413, "request exceeds kv cache capacity (kv_cache_oom)"
                )
            req = GenRequest(
                prompt=list(prompt),
                max_new_tokens=new,
                temperature=float(temperature),
                seed=seed,
                stop_token=stop_token,
            )
            try:
                self.queue.submit(req)
            except AdmissionRejected:
                with self._stats_lock:
                    self._rejected += 1
                raise
        with self._stats_lock:
            self._submitted += 1
        self._tracer.gauge("serve.queue_depth", float(self.queue.depth()))
        self._wake.set()
        return req

    def generate(self, prompt: List[int], timeout: float = 120.0, **kw: Any) -> GenRequest:
        """submit + wait: the in-process convenience the bench/tests use."""
        req = self.submit(prompt, **kw)
        if not req.done.wait(timeout):
            raise TimeoutError(f"request {req.id} did not finish in {timeout}s")
        return req

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "_EngineBase":
        if not self._thread.is_alive() and not self._finished.is_set():
            self._thread.start()
        return self

    @property
    def healthy(self) -> bool:
        """False once the loop died (crash or stop) — the liveness the
        HTTP layer and heartbeats must report, NOT thread aliveness alone
        (an unstarted engine in tests is fine)."""
        return self.failed is None and not (
            self._finished.is_set() and not self.queue.draining
        )

    def _run_guarded(self) -> None:
        """The thread target: one unexpected exception must not strand
        parked HTTP handlers on a silently dead loop — fail everything
        loudly and flip `failed` so /healthz stops claiming 'ok'."""
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 - last line of defense
            logger.exception("serving engine loop died")
            # Safe: the engine thread is the ONLY writer (exactly once, on
            # death); HTTP threads only read the GIL-atomic reference.
            self.failed = f"{type(e).__name__}: {e}"  # dtpu: lint-ok[unlocked-shared-state]
            reason = f"engine crashed: {self.failed}"
            self._fail_outstanding(reason)
            self._abort_active(reason)
            self._finished.set()

    def _abort_active(self, reason: str) -> None:
        """Fail in-flight sequences on a crash; subclasses know where
        their live lanes are."""

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, finish queued + in-flight work, stop the loop.
        Returns True when everything completed inside ``timeout``."""
        self.queue.start_drain()
        self._wake.set()
        if not self._thread.is_alive():
            return True
        self._thread.join(timeout if timeout is not None else self.cfg.drain_grace_s)
        if self._thread.is_alive():
            self.stop()
            return False
        return True

    def stop(self) -> None:
        """Hard stop: abandon in-flight work, fail outstanding requests."""
        self._stop.set()
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        self._fail_outstanding("engine stopped")

    def _finish_error(self, req: GenRequest, reason: str) -> None:
        """Fail one request AND count it: every error-finish goes through
        here so the `errored` stat the heartbeat ships stays truthful."""
        req.finish(error=reason)
        with self._stats_lock:
            self._errored += 1

    def note_http_response(self, status: int) -> None:
        """HTTP layer callback: count 5xx responses (handler failures the
        engine's own error path never sees)."""
        if status >= 500:
            with self._stats_lock:
                self._http_5xx += 1

    def _fail_outstanding(self, reason: str) -> None:
        while True:
            req = self.queue.get()
            if req is None:
                break
            self._finish_error(req, reason)

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            counters = {
                "submitted": self._submitted,
                "completed": self._completed,
                "rejected": self._rejected,
                "tokens_generated": self._tokens_generated,
                "errored": self._errored,
                "http_5xx": self._http_5xx,
                "latency_ms_avg": round(
                    self._latency_ms_total / self._completed, 3
                )
                if self._completed
                else 0.0,
            }
        kv = self.allocator.stats()
        return {
            **counters,
            "queue_depth": self.queue.depth(),
            # static queue bound: the router's saturation signal — at
            # queue_depth >= queue_capacity the next submit would 429
            "queue_capacity": self.cfg.queue_depth,
            "draining": self.queue.draining,
            # truthy once the loop died: the heartbeat ships this and the
            # master reaps the replica immediately instead of waiting out
            # the TTL behind a 500 /healthz
            "failed": self.failed,
            "kv_cache": kv,
            # live-block fraction, shared (ref>1) blocks counted ONCE so
            # prefix sharing never inflates the router's load signal
            "kv_utilization": round(kv["used"] / max(1, kv["capacity"]), 4),
            "prefix_hits": kv["prefix_hits"],
            "prefix_tokens_saved": kv["prefix_tokens_saved"],
            "prefix_hit_rate": round(
                kv["prefix_hits"] / max(1, kv["prefix_lookups"]), 4
            ),
            "uptime_s": round(time.monotonic() - self._started_at, 3),
        }

    # -- shared engine internals --------------------------------------------

    def _padded_table(self, blocks: List[int]) -> List[int]:
        return blocks + [0] * (self.cfg.blocks_per_seq - len(blocks))

    def _start_sequence(self, req: GenRequest) -> Optional[ActiveSeq]:
        """Allocate + prefill + sample the first token.  Returns the live
        sequence, or None when the request finished at prefill (wanted a
        single token).  Raises CacheOOM without side effects.

        With the prefix cache on, admission first walks the allocator's
        hash trie for the longest run of cached full blocks (capped at
        ``len(prompt) - 1`` tokens, so the block the first decode write
        lands in is never aliased — the partial tail is copy-on-write by
        re-prefilling it into a private block), maps the shared physical
        blocks into this sequence's table with a reference each, and
        prefills only the un-cached suffix.  Afterwards every full prompt
        block is registered as cached content for future admissions.
        """
        total = self.allocator.blocks_for(len(req.prompt) + req.max_new_tokens)
        shared: List[int] = []
        cached_tokens = 0
        chain: List[Any] = []
        if self.cfg.prefix_cache:
            chain = prefix_block_hashes(
                req.prompt, self.cfg.block_size, limit_tokens=len(req.prompt) - 1
            )
            shared = self.allocator.match_prefix(chain)
            cached_tokens = len(shared) * self.cfg.block_size
        needed = total - len(shared)
        try:
            with self._tracer.span("serve.kv_alloc", cat="serve", blocks=needed):
                private = self.allocator.alloc(needed)
        except CacheOOM:
            if shared:
                self.allocator.free(shared)
            raise
        blocks = shared + private
        self._tracer.gauge("serve.kv_utilization", self.allocator.utilization())
        table = self._padded_table(blocks)
        try:
            with self._tracer.span(
                "serve.prefill", cat="serve", request=req.id,
                cached_tokens=cached_tokens,
            ):
                if cached_tokens:
                    logits = self.kernels.prefill_suffix(
                        req.prompt, table, cached_tokens
                    )
                else:
                    # nothing matched: the wide single-pass prefill beats
                    # the suffix kernel's block-sequential walk (its step
                    # loop serializes what one pass runs in parallel)
                    logits = self.kernels.prefill(req.prompt, table)
        except BaseException:
            self.allocator.free(blocks)
            raise
        if chain:
            # the suffix just materialized this prompt's remaining full
            # blocks; make them matchable (shared prefix entries are
            # already in the trie — first writer wins)
            self.allocator.register_prefix(chain, blocks[: len(chain)])
        rng = np.random.default_rng(req.seed)
        tok = sample_token(logits, req.temperature, rng)
        req.first_token_at = time.monotonic()
        req.output.append(tok)
        with self._stats_lock:
            self._tokens_generated += 1
        seq = ActiveSeq(
            request=req,
            blocks=blocks,
            block_table=table,
            pos=len(req.prompt),
            next_token=tok,
            rng=rng,
        )
        if self._sequence_finished(seq, tok):
            self._retire_seq(seq)
            return None
        return seq

    def _sequence_finished(self, seq: ActiveSeq, last_token: int) -> bool:
        req = seq.request
        return len(req.output) >= req.max_new_tokens or (
            req.stop_token is not None and last_token == req.stop_token
        )

    def _retire_seq(self, seq: ActiveSeq) -> None:
        self.allocator.free(seq.blocks)
        self._tracer.gauge("serve.kv_utilization", self.allocator.utilization())
        seq.request.finish()
        latency = seq.request.latency_s
        with self._stats_lock:
            self._completed += 1
            if latency is not None:
                self._latency_ms_total += latency * 1000.0

    def _decode_batch(self, lanes: List[Optional[ActiveSeq]]) -> np.ndarray:
        """One jitted decode step over the full (static) lane table."""
        b = self.cfg.max_batch
        t = self.cfg.blocks_per_seq
        tokens = np.zeros(b, np.int32)
        positions = np.full(b, -1, np.int32)
        tables = np.zeros((b, t), np.int32)
        n_active = 0
        for i, seq in enumerate(lanes):
            if seq is None:
                continue
            tokens[i] = seq.next_token
            positions[i] = seq.pos
            tables[i] = seq.block_table
            n_active += 1
        with self._tracer.span("serve.decode", cat="serve", active=n_active):
            logits = self.kernels.decode(tokens, positions, tables)
        return logits

    def _advance_lane(self, seq: ActiveSeq, logits_row: np.ndarray) -> bool:
        """Sample the next token for one lane; True when the seq finished."""
        tok = sample_token(logits_row, seq.request.temperature, seq.rng)
        seq.request.output.append(tok)
        seq.pos += 1
        seq.next_token = tok
        with self._stats_lock:
            self._tokens_generated += 1
        return self._sequence_finished(seq, tok)

    def _run(self) -> None:  # pragma: no cover - subclasses implement
        raise NotImplementedError


class ServeEngine(_EngineBase):
    """Continuous batching: join between any two steps, retire instantly."""

    def __init__(self, kernels: DecodeKernels) -> None:
        super().__init__(kernels, thread_name="dtpu-serve-engine")
        self.lanes = LaneTable(self.cfg.max_batch)
        #: trial/model label surfaced in the master's replica listing
        self.model_label = type(kernels.model_cfg).__name__

    def _abort_active(self, reason: str) -> None:
        for i in self.lanes.active():
            seq = self.lanes.retire(i)
            self._finish_error(seq.request, reason)

    @classmethod
    def from_checkpoint(
        cls,
        path: str,
        serve_cfg: Optional[ServeConfig] = None,
        trial_class: Optional[type] = None,
    ) -> "ServeEngine":
        """Load a trial checkpoint (``train.load_trial_from_checkpoint``)
        and serve its model.  The trial's ``build_model()`` must return a
        module exposing ``cfg`` (a TransformerConfig) — the LMTrial
        contract."""
        from determined_tpu import train

        trial, trainer = train.load_trial_from_checkpoint(path, trial_class=trial_class)
        model_cfg = getattr(trainer.model, "cfg", None)
        if model_cfg is None:
            raise ValueError(
                "checkpointed trial does not build a decoder-only transformer "
                "(model has no .cfg); only TransformerLM-style trials serve"
            )
        params = trainer.state.params
        if "params" not in params:
            raise ValueError(
                "checkpoint params are in a pipeline-stage layout; serving "
                "loads single-host (pipe=1) checkpoints only"
            )
        engine = cls(DecodeKernels(model_cfg, params, serve_cfg or ServeConfig()))
        engine.model_label = type(trial).__name__  # e.g. "LMTrial"
        return engine

    def _admit_one(self) -> bool:
        """Try to move one queued request into a lane.  False when nothing
        was admitted (empty queue, or the head request must wait for cache
        blocks — it is parked at the front so FIFO order holds)."""
        req = self.queue.get()
        if req is None:
            return False
        try:
            seq = self._start_sequence(req)
        except CacheOOM:
            self.queue.requeue_head(req)
            return False
        except Exception as e:  # noqa: BLE001 - a poisoned request must not kill the loop
            logger.exception("request %d failed at prefill", req.id)
            self._finish_error(req, f"prefill failed: {e}")
            return True
        if seq is not None:
            self.lanes.join(seq)
        self._tracer.gauge("serve.queue_depth", float(self.queue.depth()))
        return True

    def step_once(self) -> bool:
        """One scheduler iteration: admit whatever fits, run one decode
        step, retire what finished.  Returns True when any work happened.
        The engine thread loops this; tests drive it directly for
        deterministic join/retire assertions (no wall-clock races)."""
        worked = False
        while self.lanes.has_free_lane() and not self._stop.is_set():
            if not self._admit_one():
                break
            worked = True
        snapshot = self.lanes.snapshot()
        if any(seq is not None for seq in snapshot):
            logits = self._decode_batch(list(snapshot))
            for i, seq in enumerate(snapshot):
                if seq is not None and self._advance_lane(seq, logits[i]):
                    self.lanes.retire(i)
                    self._retire_seq(seq)
            worked = True
        return worked

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.step_once():
                continue
            # idle: no active lanes, nothing admitted
            if self.queue.draining and self.queue.empty():
                break
            self._wake.wait(timeout=0.05)
            self._wake.clear()
        if self._stop.is_set():
            for i in self.lanes.active():
                seq = self.lanes.retire(i)
                self.allocator.free(seq.blocks)
                self._finish_error(seq.request, "engine stopped")
        self._finished.set()

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        out["lanes"] = self.lanes.stats()
        return out


class StaticBatchEngine(_EngineBase):
    """The naive baseline: form a batch, decode it to FULL completion.

    No mid-flight joins, no early retirement — a lane whose sequence
    finished early idles (position -1) until the whole batch is done.
    Exists only as the like-for-like A/B denominator in
    ``scripts/bench_serve.py``; same kernels, same admission, same
    sampling.
    """

    def __init__(self, kernels: DecodeKernels) -> None:
        super().__init__(kernels, thread_name="dtpu-serve-static")
        self._current: List[ActiveSeq] = []  # crash-abort bookkeeping

    def _abort_active(self, reason: str) -> None:
        for seq in self._current:
            if not seq.request.done.is_set():
                self._finish_error(seq.request, reason)
        self._current = []

    def _gather_batch(self) -> List[ActiveSeq]:
        batch: List[ActiveSeq] = []
        while len(batch) < self.cfg.max_batch:
            req = self.queue.get()
            if req is None:
                break
            try:
                seq = self._start_sequence(req)
            except CacheOOM:
                self.queue.requeue_head(req)
                break
            except Exception as e:  # noqa: BLE001
                self._finish_error(req, f"prefill failed: {e}")
                continue
            if seq is not None:
                batch.append(seq)
        return batch

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._current = self._gather_batch()
            if not batch:
                if self.queue.draining and self.queue.empty():
                    break
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            lanes: List[Optional[ActiveSeq]] = list(batch)
            lanes += [None] * (self.cfg.max_batch - len(lanes))
            live = [seq is not None for seq in lanes]
            while any(live) and not self._stop.is_set():
                logits = self._decode_batch(
                    [seq if live[i] else None for i, seq in enumerate(lanes)]
                )
                for i, seq in enumerate(lanes):
                    if seq is None or not live[i]:
                        continue
                    if self._advance_lane(seq, logits[i]):
                        # the RESPONSE completes now, but the lane stays
                        # occupied until the whole batch drains — that gap
                        # is exactly what continuous batching removes
                        live[i] = False
                        self._retire_seq(seq)
            if self._stop.is_set():
                for i, seq in enumerate(lanes):
                    if seq is not None and live[i]:
                        self.allocator.free(seq.blocks)
                        self._finish_error(seq.request, "engine stopped")
            self._current = []
        self._finished.set()
