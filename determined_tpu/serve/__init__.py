"""Online serving tier: continuous-batching inference over a paged KV cache.

``determined_tpu/inference.py`` is the OFFLINE path (checkpointed batch
processing of a finite dataset); this package is the ONLINE one — a
``ServeWorker`` loads a trial checkpoint, compiles prefill/decode step
functions for the decoder-only transformer (``models/transformer.py``
KV-cache decode path), and serves ``POST /v1/generate`` with:

- **continuous batching** (``engine.ServeEngine``): requests join the
  running decode batch between any two steps and retire the moment they
  finish — Orca-style iteration-level scheduling;
- a **paged KV cache** (``kv_cache.BlockAllocator`` over the block pool
  in ``models/transformer.py``): fixed-size blocks, free-list allocation,
  per-sequence block tables baked into a single decode trace;
- **bounded admission** (``scheduler.AdmissionQueue``): a full queue
  answers 429, a draining worker 503 — overload degrades into fast
  rejections, not latency collapse;
- **replica registration** (``replica.ReplicaRegistration``): workers
  register with the C++ master (``/api/v1/serving``), heartbeat, and are
  pruned on heartbeat loss, so replicas scale and discover like NTSC
  tasks.

See ``docs/serving.md`` for the architecture and request lifecycle, and
``scripts/bench_serve.py`` for the continuous-vs-static A/B.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from determined_tpu.serve.config import ServeConfig
from determined_tpu.serve.engine import (
    DecodeKernels,
    ServeEngine,
    StaticBatchEngine,
    sample_token,
)
from determined_tpu.serve.http import ServeHTTPServer
from determined_tpu.serve.kv_cache import (
    BlockAllocator,
    CacheOOM,
    prefix_block_hashes,
)
from determined_tpu.serve.replica import ReplicaRegistration
from determined_tpu.serve.scheduler import (
    AdmissionQueue,
    AdmissionRejected,
    GenRequest,
    LaneTable,
)

logger = logging.getLogger("determined_tpu.serve")

__all__ = [
    "AdmissionQueue",
    "AdmissionRejected",
    "BlockAllocator",
    "CacheOOM",
    "DecodeKernels",
    "GenRequest",
    "LaneTable",
    "ReplicaRegistration",
    "ServeConfig",
    "prefix_block_hashes",
    "ServeEngine",
    "ServeHTTPServer",
    "ServeWorker",
    "StaticBatchEngine",
    "sample_token",
]


class ServeWorker:
    """One serving replica: engine + HTTP server + optional registration.

    The CLI (``dtpu serve``) builds one of these; tests drive it
    in-process.  ``request_drain`` is idempotent and safe to call from the
    main thread after a signal flag flips (never call it FROM a signal
    handler — it touches Events; see ``cli/main.py serve_cmd`` for the
    flag-poll pattern the handler uses instead).
    """

    def __init__(
        self,
        engine: ServeEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        session: Optional[Any] = None,
        model: str = "",
        checkpoint: str = "",
        model_name: str = "",
        model_version: int = 0,
        task_id: str = "",
    ) -> None:
        self.engine = engine
        self.http = ServeHTTPServer(engine, host=host, port=port)
        self._session = session
        self._model = model
        self._checkpoint = checkpoint
        self._model_name = model_name
        self._model_version = model_version
        self._task_id = task_id
        self.replica: Optional[ReplicaRegistration] = None
        # set from the heartbeat thread when the master asks this replica
        # to drain (rolling deploy); plain attribute writes so the serve
        # main loop can poll it next to its signal flag
        self._master_drain = False
        self.master_drain_info: Dict[str, Any] = {}

    def start(self) -> str:
        """Start engine + HTTP (+ master registration when a session was
        given); returns the URL the replica serves on."""
        self.engine.start()
        self.http.start()
        if self._session is not None:
            self.replica = ReplicaRegistration(
                self._session,
                url=self.http.url,
                model=self._model,
                checkpoint=self._checkpoint,
                model_name=self._model_name,
                model_version=self._model_version,
                task_id=self._task_id,
                heartbeat_interval_s=self.engine.cfg.heartbeat_interval_s,
                stats_fn=self.engine.stats,
                on_drain=self._on_master_drain,
            ).start()
        logger.info("serving replica up at %s", self.http.url)
        return self.http.url

    def _on_master_drain(self, info: Dict[str, Any]) -> None:
        # heartbeat-thread context: attribute writes only (the main loop
        # polls master_drain_requested and runs the actual drain)
        self.master_drain_info = dict(info)
        self._master_drain = True

    def master_drain_requested(self) -> bool:
        """True once the master's heartbeat response asked for a drain
        (rolling deploy walking this replica)."""
        return self._master_drain

    def request_drain(self) -> None:
        """Close admission: /healthz flips to draining, new generations
        get 503, queued + in-flight requests run to completion."""
        self.http.start_drain()
        self.engine.queue.start_drain()
        self.engine._wake.set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until the engine finished its queued + in-flight work."""
        return self.engine.drain(timeout=timeout)

    def shutdown(self, deregister: bool = True) -> None:
        if self.replica is not None:
            self.replica.close(deregister=deregister)
            self.replica = None
        self.engine.stop()
        self.http.stop()

    def stats(self) -> Dict[str, Any]:
        out = self.engine.stats()
        out["url"] = self.http.url if self.http.running else None
        if self.replica is not None:
            out["replica_id"] = self.replica.replica_id
        return out
