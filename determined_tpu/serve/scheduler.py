"""Continuous-batching scheduler: requests, the bounded admission queue,
and the decode-lane table.

Iteration-level (continuous) batching as in Orca (Yu et al., OSDI '22):
the unit of scheduling is one decode STEP, not one request.  New sequences
join the running batch between steps the moment a lane and cache blocks
are free, and finished sequences retire immediately — a short completion
never waits for a long neighbor the way static batching forces it to.

The admission queue is the bounded-queue backpressure pattern of
``data/_prefetch.py`` turned outward: when the queue is full the HTTP
layer answers 429 instead of buffering unboundedly, so overload degrades
into fast rejections rather than latency collapse.  FIFO order through
the queue is the fairness contract — the engine never reorders admissions,
it only delays them when the cache cannot fit the head request yet.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

_req_ids = itertools.count(1)


@dataclasses.dataclass
class GenRequest:
    """One generation request riding through admission -> decode -> retire.

    The HTTP handler thread blocks on ``done``; the engine thread fills the
    result fields before setting it.  No lock: each field has exactly one
    writer (the engine) and readers only look after ``done`` is set.
    """

    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: Optional[int] = None
    stop_token: Optional[int] = None  # generation ends early on this token
    id: int = dataclasses.field(default_factory=lambda: next(_req_ids))
    arrival: float = dataclasses.field(default_factory=time.monotonic)
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    # -- results (engine-written) -------------------------------------------
    output: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    first_token_at: Optional[float] = None  # monotonic, for TTFT
    finished_at: Optional[float] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.arrival

    @property
    def latency_s(self) -> Optional[float]:
        if self.finished_at is None:
            return None
        return self.finished_at - self.arrival

    def finish(self, error: Optional[str] = None) -> None:
        self.error = error
        self.finished_at = time.monotonic()
        self.done.set()


class AdmissionRejected(Exception):
    """Request refused at the door; ``status`` is the HTTP code to answer."""

    def __init__(self, status: int, reason: str) -> None:
        super().__init__(reason)
        self.status = status
        self.reason = reason


class AdmissionQueue:
    """Bounded FIFO between the HTTP threads and the engine loop.

    ``submit`` never blocks: a full queue raises :class:`AdmissionRejected`
    (429), a draining queue rejects everything new (503).  The engine side
    uses ``get``/``requeue_head``; ``requeue_head`` preserves FIFO when the
    head request could not be admitted yet (cache full) — it goes back to
    the FRONT, so later arrivals cannot starve it.
    """

    def __init__(self, depth: int) -> None:
        self._q: "queue.Queue[GenRequest]" = queue.Queue(maxsize=depth)
        self._head_lock = threading.Lock()
        self._head: Optional[GenRequest] = None  # requeued front-of-line item
        self._draining = False  # plain-bool flag; set once, GIL-atomic

    # -- producer side (HTTP threads) ---------------------------------------

    def submit(self, req: GenRequest) -> None:
        if self._draining:
            raise AdmissionRejected(503, "draining")
        try:
            self._q.put(req, block=False)
        except queue.Full:
            raise AdmissionRejected(429, "admission queue full") from None

    # -- consumer side (engine thread) --------------------------------------

    def get(self, timeout: float = 0.0) -> Optional[GenRequest]:
        with self._head_lock:
            if self._head is not None:
                head, self._head = self._head, None
                return head
        try:
            if timeout <= 0:
                return self._q.get(block=False)
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def requeue_head(self, req: GenRequest) -> None:
        with self._head_lock:
            if self._head is not None:
                raise RuntimeError("only one head request may be parked")
            self._head = req

    # -- drain / inspection --------------------------------------------------

    def start_drain(self) -> None:
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        with self._head_lock:
            head = 1 if self._head is not None else 0
        return self._q.qsize() + head

    def empty(self) -> bool:
        return self.depth() == 0


@dataclasses.dataclass
class ActiveSeq:
    """One decode lane's state: request + cache bookkeeping."""

    request: GenRequest
    blocks: List[int]               # physical block ids owned by this seq
    block_table: List[int]          # padded to blocks_per_seq with scratch 0
    pos: int                        # position of the NEXT token to feed
    next_token: int                 # token to feed at `pos`
    rng: Any = None                 # np.random.Generator for sampling

    @property
    def generated(self) -> int:
        return len(self.request.output)


class LaneTable:
    """The fixed array of decode lanes the jitted step batches over.

    Mutated only by the engine thread; the lock exists for the ``/stats``
    reader and for tests, not for engine-vs-engine races.
    """

    def __init__(self, max_batch: int) -> None:
        self._lock = threading.Lock()
        self._lanes: List[Optional[ActiveSeq]] = [None] * max_batch
        self.joined = 0
        self.retired = 0

    def join(self, seq: ActiveSeq) -> int:
        """Place ``seq`` into the lowest free lane; raises if none free
        (the engine checks ``has_free_lane`` first)."""
        with self._lock:
            for i, lane in enumerate(self._lanes):
                if lane is None:
                    self._lanes[i] = seq
                    self.joined += 1
                    return i
        raise RuntimeError("no free decode lane")

    def retire(self, lane: int) -> ActiveSeq:
        with self._lock:
            seq = self._lanes[lane]
            if seq is None:
                raise RuntimeError(f"lane {lane} already empty")
            self._lanes[lane] = None
            self.retired += 1
            return seq

    def has_free_lane(self) -> bool:
        with self._lock:
            return any(lane is None for lane in self._lanes)

    def active(self) -> List[int]:
        """Indices of occupied lanes."""
        with self._lock:
            return [i for i, lane in enumerate(self._lanes) if lane is not None]

    def get(self, lane: int) -> Optional[ActiveSeq]:
        with self._lock:
            return self._lanes[lane]

    def snapshot(self) -> Sequence[Optional[ActiveSeq]]:
        with self._lock:
            return list(self._lanes)

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for lane in self._lanes if lane is not None)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "lanes": len(self._lanes),
                "active": sum(1 for lane in self._lanes if lane is not None),
                "joined": self.joined,
                "retired": self.retired,
            }
