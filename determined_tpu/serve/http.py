"""The replica's HTTP surface: /v1/generate, /healthz, /stats.

A thin stdlib ``ThreadingHTTPServer`` — each request thread parks on its
``GenRequest.done`` event while the engine thread does the work, so the
server needs no async machinery and the engine stays the only place model
code runs.  Backpressure surfaces as status codes, never as buffering:
429 when the admission queue is full, 503 once draining starts, 413 for
requests the replica could never fit.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from determined_tpu.serve.engine import _EngineBase
from determined_tpu.serve.scheduler import AdmissionRejected
from determined_tpu.utils import faults

logger = logging.getLogger("determined_tpu.serve.http")

#: generous ceiling on how long one response may take end to end; a
#: request admitted but stuck longer than this answers 504
REQUEST_TIMEOUT_S = 600.0


class ServeHTTPServer:
    """Bind the engine to an HTTP port.  ``start()`` returns the bound
    port (pass port 0 to let the OS choose — tests and multi-replica
    hosts)."""

    def __init__(self, engine: _EngineBase, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        self.host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.draining = False  # plain flag: flipped once by the drain path

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        engine = self.engine
        server = self

        class Handler(BaseHTTPRequestHandler):
            # stdlib default logs every request to stderr; route to logging
            def log_message(self, fmt: str, *args: Any) -> None:  # noqa: N802
                logger.debug("%s " + fmt, self.client_address[0], *args)

            def _reply(self, status: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except BrokenPipeError:  # client gave up; nothing to do
                    pass

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz":
                    if not engine.healthy:
                        self._reply(500, {"status": "failed",
                                          "error": engine.failed})
                    elif server.draining:
                        self._reply(503, {"status": "draining"})
                    else:
                        self._reply(200, {"status": "ok"})
                elif self.path == "/stats":
                    self._reply(200, engine.stats())
                else:
                    self._reply(404, {"error": f"no such path: {self.path}"})

            def do_POST(self) -> None:  # noqa: N802
                if self.path != "/v1/generate":
                    self._reply(404, {"error": f"no such path: {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._reply(400, {"error": "bad json"})
                    return
                try:
                    status, payload = server.handle_generate(body)
                except Exception as e:  # noqa: BLE001 - a failed handler must still answer
                    logger.exception("/v1/generate handler failed")
                    status = 500
                    payload = {"error": f"handler failed: {e}"}
                    # handler-level 5xx the engine's own error path never
                    # saw: count it so heartbeat stats stay truthful
                    engine.note_http_response(status)
                self._reply(status, payload)

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="dtpu-serve-http",
            daemon=True,
        )
        self._thread.start()
        return self.port

    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def port(self) -> int:
        assert self._httpd is not None, "server not started"
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start_drain(self) -> None:
        """Flip /healthz to draining and reject new generations; in-flight
        handler threads keep their connections until their requests
        finish."""
        self.draining = True

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- request handling (runs on handler threads) --------------------------

    def handle_generate(self, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        if self.draining:
            return 503, {"error": "draining"}
        # chaos hook: an installed injector raising here surfaces as a
        # counted 500 — how the selfheal smoke manufactures an error-rate
        # regression on a canary cohort
        faults.fire("serve.generate")
        prompt = body.get("prompt_tokens")
        if not isinstance(prompt, list) or not all(
            isinstance(t, int) for t in prompt
        ):
            return 400, {"error": "prompt_tokens must be a list of ints"}
        try:
            # type coercion INSIDE the guard: a malformed field is a 400,
            # never an unanswered connection from a crashed handler
            max_new = body.get("max_new_tokens")
            seed = body.get("seed")
            stop = body.get("stop_token")
            req = self.engine.submit(
                prompt,
                max_new_tokens=None if max_new is None else int(max_new),
                temperature=float(body.get("temperature", 0.0)),
                seed=None if seed is None else int(seed),
                stop_token=None if stop is None else int(stop),
            )
        except AdmissionRejected as e:
            return e.status, {"error": e.reason}
        except (TypeError, ValueError) as e:
            return 400, {"error": f"bad request field: {e}"}
        if not req.done.wait(REQUEST_TIMEOUT_S):
            self.engine.note_http_response(504)
            return 504, {"error": "generation timed out", "request_id": req.id}
        if req.error:
            # already counted by the engine's _finish_error; http_5xx only
            # tracks failures the engine did NOT see
            return 500, {"error": req.error, "request_id": req.id}
        return 200, {
            "request_id": req.id,
            "tokens": req.output,
            "usage": {
                "prompt_tokens": len(req.prompt),
                "completion_tokens": len(req.output),
            },
            "ttft_ms": round((req.ttft_s or 0.0) * 1e3, 2),
            "latency_ms": round((req.latency_s or 0.0) * 1e3, 2),
        }
