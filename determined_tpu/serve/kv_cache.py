"""Paged KV-cache block allocator: the host-side half of PagedAttention.

The device arrays (``models/transformer.py init_kv_cache``) are a flat pool
of fixed-size blocks; this module owns WHICH blocks belong to WHOM.  A
free-list allocator hands out physical block ids all-or-nothing per
sequence (admission either fits a whole worst-case request or rejects it —
no mid-flight OOM aborting a half-generated response), and frees them the
moment the sequence retires, so cache capacity — not lane count — is the
real admission limit under long-context load.

Block 0 is reserved as the scratch block padded prefill positions and
inactive decode lanes write into (static scatter shapes, no masking in the
kernel); it is never handed out and never freed.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks a sequence of ``n_tokens`` spans — THE sizing formula.
    ServeConfig validation and the allocator both call this one function,
    so admission limits and placement can never disagree."""
    return -(-max(int(n_tokens), 1) // block_size)


class CacheOOM(Exception):
    """Not enough free blocks to admit the sequence right now."""

    def __init__(self, needed: int, free: int) -> None:
        super().__init__(f"kv cache exhausted: need {needed} blocks, {free} free")
        self.needed = needed
        self.free = free


class BlockAllocator:
    """Thread-safe free-list over physical block ids ``1..num_blocks-1``.

    LIFO reuse on purpose: a just-freed block is handed out next, so the
    hot working set of physical blocks stays small and (on TPU) resident
    in whatever cache hierarchy backs HBM reads.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))  # pop() -> 1 first
        self._allocated: set = set()
        self.peak_in_use = 0

    # -- sizing --------------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Usable blocks (excludes the scratch block)."""
        return self.num_blocks - 1

    def blocks_for(self, n_tokens: int) -> int:
        return blocks_for_tokens(n_tokens, self.block_size)

    # -- alloc / free --------------------------------------------------------

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks or raise :class:`CacheOOM` taking none."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        with self._lock:
            if n > len(self._free):
                raise CacheOOM(n, len(self._free))
            blocks = [self._free.pop() for _ in range(n)]
            self._allocated.update(blocks)
            self.peak_in_use = max(self.peak_in_use, len(self._allocated))
            return blocks

    def free(self, blocks: Sequence[int]) -> None:
        """Return blocks to the pool; double-free and foreign ids are
        programming errors and raise (a silently recycled block would
        corrupt another sequence's cache)."""
        with self._lock:
            for b in blocks:
                if b not in self._allocated:
                    raise ValueError(f"free of unallocated block {b}")
                self._allocated.remove(b)
                self._free.append(b)

    # -- inspection ----------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return len(self._allocated)

    def utilization(self) -> float:
        with self._lock:
            return len(self._allocated) / max(1, self.capacity)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "used": len(self._allocated),
                "free": len(self._free),
                "peak": self.peak_in_use,
                "block_size": self.block_size,
            }
