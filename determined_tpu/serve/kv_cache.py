"""Paged KV-cache block allocator: the host-side half of PagedAttention.

The device arrays (``models/transformer.py init_kv_cache``) are a flat pool
of fixed-size blocks; this module owns WHICH blocks belong to WHOM.  A
ref-counted free-list allocator hands out physical block ids all-or-nothing
per sequence (admission either fits a whole worst-case request or rejects
it — no mid-flight OOM aborting a half-generated response), and releases
them the moment the sequence retires, so cache capacity — not lane count —
is the real admission limit under long-context load.

Prefix caching (vLLM-style, Kwon et al. SOSP'23) rides the same allocator:
every FULL block of a prompt is content-addressed by the hash chain
``h_i = hash((h_{i-1}, tokens_i))`` — each link covers one block's tokens
and transitively its whole prefix, so a flat ``hash -> physical block``
map IS a prefix trie (a child is only reachable through its parent's
hash).  Admission walks the chain and maps the longest cached run of
physical blocks into the new sequence's table with an incref per block;
only the un-cached suffix is prefilled.  Shared blocks are strictly
read-only: the partial tail block (and the block holding the final prompt
token, which the first decode write may touch) is never aliased — it is
copy-on-write in the recompute sense, re-prefilled into a private block.
When a sequence retires, registered blocks whose refcount hits zero move
to a resident LRU pool instead of the free list; they stay matchable and
are evicted (oldest first) only when ``alloc`` runs out of truly free
blocks.  Eviction therefore never touches a block with live references.

Block 0 is reserved as the scratch block padded prefill positions and
inactive decode lanes write into (static scatter shapes, no masking in the
kernel); it is never handed out and never freed.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

#: root of every hash chain — an arbitrary odd constant so the first
#: block's hash differs from hash of its tokens alone
_HASH_ROOT = 0x9E3779B97F4A7C15


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks a sequence of ``n_tokens`` spans — THE sizing formula.
    ServeConfig validation and the allocator both call this one function,
    so admission limits and placement can never disagree."""
    return -(-max(int(n_tokens), 1) // block_size)


def prefix_block_hashes(
    tokens: Sequence[int], block_size: int, limit_tokens: Optional[int] = None
) -> List[Tuple[int, Tuple[int, ...]]]:
    """Hash chain over the FULL blocks of ``tokens``: ``[(h_i, chunk_i)]``.

    Only complete blocks participate (a partial tail is never shared), and
    ``limit_tokens`` caps how many tokens the chain may cover — admission
    passes ``len(prompt) - 1`` so at least the final prompt token is always
    re-prefilled privately (its logits seed sampling, and the first decode
    write can land in its block)."""
    n = len(tokens)
    if limit_tokens is not None:
        n = min(n, max(0, int(limit_tokens)))
    out: List[Tuple[int, Tuple[int, ...]]] = []
    h = _HASH_ROOT
    for i in range(n // block_size):
        chunk = tuple(int(t) for t in tokens[i * block_size : (i + 1) * block_size])
        h = hash((h, chunk))
        out.append((h, chunk))
    return out


class CacheOOM(Exception):
    """Not enough free blocks to admit the sequence right now."""

    def __init__(self, needed: int, free: int) -> None:
        super().__init__(f"kv cache exhausted: need {needed} blocks, {free} free")
        self.needed = needed
        self.free = free


class BlockAllocator:
    """Thread-safe ref-counted allocator over physical block ids
    ``1..num_blocks-1`` with an optional prefix cache.

    LIFO reuse on purpose: a just-freed block is handed out next, so the
    hot working set of physical blocks stays small and (on TPU) resident
    in whatever cache hierarchy backs HBM reads.  Cached (refcount-0 but
    matchable) blocks are only consumed once the free list is empty, so
    prefix reuse never fights short-lived allocations for block ids.
    """

    def __init__(
        self, num_blocks: int, block_size: int, prefix_cache: bool = False
    ) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_cache = bool(prefix_cache)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))  # pop() -> 1 first
        #: live blocks -> reference count (shared prefix blocks count > 1)
        self._ref: Dict[int, int] = {}
        #: refcount-0 blocks still holding registered prefix content;
        #: insertion order is release order, so popping from the front
        #: evicts least-recently-released first (LRU)
        self._cached: "OrderedDict[int, int]" = OrderedDict()  # block -> hash
        #: the trie: chain hash -> physical block (live or cached)
        self._prefix: Dict[int, int] = {}
        #: reverse map for eviction/unregistration
        self._block_hash: Dict[int, int] = {}
        self.peak_in_use = 0
        # -- prefix counters (ride heartbeat stats) ---------------------------
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.evictions = 0

    # -- sizing --------------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Usable blocks (excludes the scratch block)."""
        return self.num_blocks - 1

    def blocks_for(self, n_tokens: int) -> int:
        return blocks_for_tokens(n_tokens, self.block_size)

    # -- alloc / free --------------------------------------------------------

    def _evict_one_locked(self) -> int:
        """Reclaim the least-recently-released cached block.  Only ever
        touches refcount-0 blocks — live blocks are not in ``_cached``."""
        block, h = self._cached.popitem(last=False)
        assert block not in self._ref, "cached block has live references"
        self._prefix.pop(h, None)
        self._block_hash.pop(block, None)
        self.evictions += 1
        return block

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` private blocks (refcount 1 each) or raise
        :class:`CacheOOM` taking none.  Under pressure, refcount-0 cached
        prefix blocks are evicted LRU-first to satisfy the request."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        with self._lock:
            if n > len(self._free) + len(self._cached):
                raise CacheOOM(n, len(self._free) + len(self._cached))
            blocks: List[int] = []
            for _ in range(n):
                if self._free:
                    blocks.append(self._free.pop())
                else:
                    blocks.append(self._evict_one_locked())
            for b in blocks:
                self._ref[b] = 1
            self.peak_in_use = max(self.peak_in_use, len(self._ref))
            return blocks

    def share(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already live) block — the caller now
        co-owns them and must ``free`` them exactly once."""
        with self._lock:
            for b in blocks:
                if b not in self._ref:
                    raise ValueError(f"share of unallocated block {b}")
            for b in blocks:
                self._ref[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; a block whose count hits zero
        returns to the pool (or parks in the prefix cache if registered).
        Over-freeing — more frees than references — and foreign ids are
        programming errors and raise (a silently recycled block would
        corrupt another sequence's cache)."""
        with self._lock:
            for b in blocks:
                if b not in self._ref:
                    raise ValueError(f"free of unallocated block {b}")
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] > 0:
                    continue
                del self._ref[b]
                h = self._block_hash.get(b)
                if self.prefix_cache and h is not None and self._prefix.get(h) == b:
                    # still the canonical block for its prefix hash: keep it
                    # resident and matchable until eviction wants it back
                    self._cached[b] = h
                    self._cached.move_to_end(b)
                else:
                    if h is not None:
                        self._block_hash.pop(b, None)
                    self._free.append(b)

    # -- prefix cache --------------------------------------------------------

    def match_prefix(
        self, chain: Sequence[Tuple[int, Tuple[int, ...]]]
    ) -> List[int]:
        """Walk ``chain`` (from :func:`prefix_block_hashes`) through the
        trie and take a reference on every block of the longest cached
        run.  Returns the physical blocks, root-first; the caller owns one
        reference per block and releases it via ``free`` at retirement."""
        with self._lock:
            self.prefix_lookups += 1
            matched: List[int] = []
            for h, _chunk in chain:
                b = self._prefix.get(h)
                if b is None:
                    break
                matched.append(b)
            for b in matched:
                if b in self._cached:
                    del self._cached[b]
                    self._ref[b] = 1
                else:
                    self._ref[b] += 1
            if matched:
                self.prefix_hits += 1
                self.prefix_tokens_saved += len(matched) * self.block_size
            self.peak_in_use = max(self.peak_in_use, len(self._ref))
            return matched

    def register_prefix(
        self,
        chain: Sequence[Tuple[int, Tuple[int, ...]]],
        blocks: Sequence[int],
    ) -> None:
        """Record ``blocks[i]`` as the canonical holder of ``chain[i]``'s
        content.  First writer wins: a hash already in the trie keeps its
        existing block (both hold identical content — content addressing
        makes the duplicate harmless, dedup only matters for future
        matches).  Blocks must be live (ref >= 1)."""
        if not self.prefix_cache:
            return
        with self._lock:
            for (h, _chunk), b in zip(chain, blocks):
                if h in self._prefix:
                    continue
                if b not in self._ref or b in self._block_hash:
                    continue
                self._prefix[h] = b
                self._block_hash[b] = h

    # -- inspection ----------------------------------------------------------

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Distinct live blocks — a block shared by N sequences counts ONCE
        (the router's load signal must not be inflated by sharing)."""
        with self._lock:
            return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks parked in the prefix cache (reclaimable)."""
        with self._lock:
            return len(self._cached)

    def utilization(self) -> float:
        """Live-block fraction of capacity; cached-but-reclaimable blocks
        do not count (they yield to any allocation)."""
        with self._lock:
            return len(self._ref) / max(1, self.capacity)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "used": len(self._ref),
                "free": len(self._free),
                "cached": len(self._cached),
                "peak": self.peak_in_use,
                "block_size": self.block_size,
                "prefix_lookups": self.prefix_lookups,
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_saved": self.prefix_tokens_saved,
                "evictions": self.evictions,
            }
