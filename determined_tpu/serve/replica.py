"""Replica registration: make a serving worker discoverable via the master.

The reference platform reverse-proxies NTSC tasks that register with the
master (SURVEY §3.5); serving replicas follow the same contract one level
simpler — a replica POSTs itself to ``/api/v1/serving/replicas`` with the
URL it listens on, heartbeats on an interval, and the master prunes any
replica whose heartbeat goes stale (crash, partition, SIGKILL), so
``GET /api/v1/serving`` is always the live routing table.  A heartbeat
answered 404 means the master forgot us (restart, prune race): the thread
re-registers with the same payload rather than dying.

The heartbeat response is also the master's only channel TO the worker:
during a rolling deploy (``POST /api/v1/serving/deploy``) the master
answers the draining replica's heartbeat with ``{"drain": true, "deploy":
{...target...}}`` — the worker then runs its normal drain (503-new,
finish in-flight, deregister, exit 75) and whatever supervises it
relaunches it on the target version.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Callable, Dict, Optional

import requests

from determined_tpu.api.session import APIError, NotFoundError, Session

logger = logging.getLogger("determined_tpu.serve.replica")

#: ceiling on the heartbeat's 429 backoff — stay a couple of TTL windows
#: under the master's reap horizon while still easing off a shedding master
MAX_THROTTLE_S = 30.0


class ReplicaRegistration:
    """Owns the replica's master-side lifecycle + the heartbeat thread."""

    def __init__(
        self,
        session: Session,
        *,
        url: str,
        model: str = "",
        checkpoint: str = "",
        model_name: str = "",
        model_version: int = 0,
        task_id: str = "",
        heartbeat_interval_s: float = 2.0,
        stats_fn: Optional[Any] = None,
        on_drain: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> None:
        self._session = session
        self._payload: Dict[str, Any] = {
            "url": url,
            "model": model,
            "checkpoint": checkpoint,
        }
        if model_name:
            # registry-launched (--model name@version): the resolved
            # version rides registration so the listing shows it
            self._payload["model_name"] = model_name
            self._payload["model_version"] = int(model_version)
        if task_id:
            # supervisor-launched: lets the master's fleet supervisor bind
            # this replica back to the slot whose task is running it
            self._payload["task_id"] = task_id
        self._interval = heartbeat_interval_s
        #: called once (from the heartbeat thread) when the master's
        #: heartbeat response asks this replica to drain (rolling deploy)
        self._on_drain = on_drain
        self.drain_requested = threading.Event()
        self.drain_info: Dict[str, Any] = {}
        #: zero-arg callable whose dict rides each heartbeat, surfacing
        #: queue depth / kv utilization in the master's replica listing
        self._stats_fn = stats_fn
        self._lock = threading.Lock()  # guards replica_id + throttled
        self.replica_id: Optional[str] = None
        #: consecutive 429s from the master's admission control; each one
        #: stretches the next heartbeat exponentially (jittered, capped)
        #: instead of hammering a shedding master on the fixed cadence
        self.throttled = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _throttle_delay(self, retry_after: Optional[str] = None) -> float:
        """Next heartbeat delay after ``self.throttled`` consecutive 429s:
        the master's ``Retry-After`` (seconds form) when given, else
        capped exponential backoff off the base interval with +/-50%
        jitter so a throttled fleet doesn't re-stampede in lockstep."""
        if retry_after:
            try:
                return max(float(retry_after), 0.0)
            except ValueError:
                pass  # HTTP-date form: fall through to computed backoff
        with self._lock:
            throttled = self.throttled
        return min(
            MAX_THROTTLE_S,
            self._interval * (2 ** max(throttled, 1)) * random.uniform(0.5, 1.5),
        )

    # -- registration --------------------------------------------------------

    def register(self) -> str:
        resp = self._session.post(
            "/api/v1/serving/replicas", json=dict(self._payload), retry=True
        )
        rid = resp.json()["id"]
        with self._lock:
            self.replica_id = rid
        logger.info("registered serving replica %s (%s)", rid, self._payload["url"])
        return rid

    def start(self) -> "ReplicaRegistration":
        """Register and keep the registration alive in the background."""
        self.register()
        self._thread = threading.Thread(
            target=self._run, name="dtpu-serve-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    # -- heartbeat loop ------------------------------------------------------

    def _run(self) -> None:
        delay = self._interval
        while not self._stop.wait(delay):
            delay = self._interval  # 429 handling below stretches this
            with self._lock:
                rid = self.replica_id
            if rid is None:
                continue
            body: Dict[str, Any] = {}
            if self._stats_fn is not None:
                try:
                    body["stats"] = self._stats_fn()
                except Exception:  # noqa: BLE001 - stats must not kill liveness
                    logger.exception("stats collection failed; heartbeat without")
            try:
                resp = self._session.post(
                    f"/api/v1/serving/replicas/{rid}/heartbeat",
                    json=body,
                    retry=False,
                )
                with self._lock:
                    self.throttled = 0
                self._handle_heartbeat_response(resp)
            except NotFoundError:
                # master forgot us (restart or prune race): re-register.
                # The catch is deliberately broad — register() uses a
                # retrying POST whose terminal failure is a ConnectionError
                # (master still down), and an exception escaping THIS
                # except-block would kill the heartbeat thread for good;
                # the worker must keep serving and keep retrying instead.
                logger.warning("replica %s unknown to master; re-registering", rid)
                try:
                    self.register()
                    with self._lock:
                        self.throttled = 0
                except APIError as e:
                    if e.status == 429:
                        # admission control sheds re-registrations too:
                        # ease off instead of re-stampeding every interval
                        with self._lock:
                            self.throttled += 1
                        delay = self._throttle_delay(e.retry_after)
                        logger.warning(
                            "re-registration of replica %s shed (429); "
                            "retrying in %.1fs", rid, delay,
                        )
                    else:
                        logger.warning(
                            "re-registration of replica %s failed (HTTP %d); "
                            "will retry on the next heartbeat", rid, e.status,
                        )
                except (requests.ConnectionError, requests.Timeout):
                    # routine during a master restart window: warn without a
                    # traceback (this repeats every interval until it lands)
                    logger.warning(
                        "re-registration of replica %s failed (master still "
                        "unreachable?); will retry on the next heartbeat", rid,
                    )
                except Exception:  # noqa: BLE001 - survive, but keep the trace
                    logger.exception(
                        "re-registration of replica %s failed; will retry", rid
                    )
            except (requests.ConnectionError, requests.Timeout):
                # master down/restarting: a serving replica keeps serving
                # through a control-plane outage and re-registers when the
                # master's heartbeat 404 says it forgot us
                logger.warning(
                    "master unreachable; heartbeat for replica %s will retry", rid
                )
            except APIError as e:
                if e.status == 429:
                    # the master's WAL admission control is shedding load
                    # (PR-13): back off — the TTL is sized in intervals, so
                    # the capped delay keeps us alive while easing pressure
                    with self._lock:
                        self.throttled += 1
                    delay = self._throttle_delay(e.retry_after)
                    logger.warning(
                        "heartbeat for replica %s shed (429); next in %.1fs",
                        rid, delay,
                    )
                else:
                    # transient master trouble: keep beating, the master-side
                    # TTL is several intervals wide
                    logger.warning("heartbeat failed for replica %s", rid)
            except Exception:  # noqa: BLE001 - the heartbeat must survive
                logger.exception("heartbeat error for replica %s", rid)

    def _handle_heartbeat_response(self, resp: Any) -> None:
        """The master's answer may carry a rolling-deploy drain request."""
        try:
            data = resp.json()
        except ValueError:
            return
        if not isinstance(data, dict) or not data.get("drain"):
            return
        if not self.drain_requested.is_set():
            # safe unlocked: published BEFORE drain_requested.set(), and
            # every reader gates on that Event (release/acquire ordering)
            # dtpu: lint-ok[unlocked-shared-state]
            self.drain_info = dict(data.get("deploy") or {})
            self.drain_requested.set()
            logger.info(
                "master requested drain (rolling deploy -> %s)",
                self.drain_info.get("target") or "?",
            )
            if self._on_drain is not None:
                try:
                    self._on_drain(self.drain_info)
                except Exception:  # noqa: BLE001 - must not kill the heartbeat
                    logger.exception("on_drain callback failed")

    # -- shutdown ------------------------------------------------------------

    def close(self, deregister: bool = True) -> None:
        """Stop heartbeating; optionally remove the master-side record so
        a drained replica disappears immediately instead of at TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 2 * self._interval))
            self._thread = None
        with self._lock:
            rid, self.replica_id = self.replica_id, None
        if deregister and rid is not None:
            try:
                self._session.delete(f"/api/v1/serving/replicas/{rid}")
            except (APIError, requests.ConnectionError, requests.Timeout):
                logger.warning("deregistration of %s failed (master will prune)", rid)
