"""ServeConfig: the sizing knobs of one serving replica.

Every shape the jitted prefill/decode steps trace over comes from here —
lane count, prompt padding, block-table width — so the config is also the
retrace contract: two requests that differ only in length run through the
same compiled program.  ``docs/serving.md`` explains how to size the cache
(``num_blocks``) against HBM and expected sequence lengths.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from determined_tpu.config.experiment import InvalidExperimentConfig


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    # ---- paged KV cache ---------------------------------------------------
    #: tokens per cache block (vLLM-style fixed-size pages)
    block_size: int = 16
    #: physical blocks in the pool; block 0 is the scratch block padded
    #: writes land in, so usable capacity is (num_blocks - 1) * block_size
    num_blocks: int = 256
    # ---- continuous batching ----------------------------------------------
    #: decode lanes: max sequences in flight per step (static batch shape)
    max_batch: int = 8
    #: prompts are padded to this length for the single prefill trace
    max_prompt_len: int = 128
    #: cap on tokens generated per request (requests may ask for fewer)
    max_new_tokens: int = 64
    # ---- admission --------------------------------------------------------
    #: bounded request queue depth; a full queue rejects with 429
    queue_depth: int = 16
    # ---- fast path --------------------------------------------------------
    #: share full KV blocks across requests with a common prompt prefix
    #: (content-addressed hash trie in the allocator); admission then only
    #: prefills the un-cached suffix.  Off restores the PR-9 data path.
    prefix_cache: bool = True
    #: lazy paged decode: gather the block table in chunks of this many
    #: columns per attention pass, running only ceil((pos+1)/chunk) passes
    #: instead of materializing the whole table every step.  0 = legacy
    #: full-table gather.  Must divide blocks_per_seq so every chunk is a
    #: full dynamic slice of the table.
    decode_chunk_blocks: int = 1
    # ---- http / replica ---------------------------------------------------
    host: str = "127.0.0.1"
    port: int = 8001
    #: master heartbeat period (seconds) when registered
    heartbeat_interval_s: float = 2.0
    #: how long a SIGTERM drain waits for in-flight work before giving up
    drain_grace_s: float = 30.0

    def __post_init__(self) -> None:
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is scratch), got {self.num_blocks}"
            )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_prompt_len < 1 or self.max_new_tokens < 1:
            raise ValueError("max_prompt_len and max_new_tokens must be >= 1")
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self.queue_depth}")
        needed = self.blocks_for(self.max_prompt_len + self.max_new_tokens)
        if needed > self.usable_blocks:
            raise ValueError(
                f"cache too small: a worst-case request needs {needed} blocks "
                f"but only {self.usable_blocks} are usable "
                "(raise num_blocks or lower max_prompt_len/max_new_tokens)"
            )
        if self.decode_chunk_blocks < 0:
            raise InvalidExperimentConfig(
                f"decode_chunk_blocks must be >= 0, got {self.decode_chunk_blocks}"
            )
        if self.decode_chunk_blocks and self.blocks_per_seq % self.decode_chunk_blocks:
            # the lazy decode slides a fixed-width window over the table;
            # a chunk that doesn't divide the pool would leave a ragged
            # final slice the static trace can't express
            raise InvalidExperimentConfig(
                f"decode_chunk_blocks={self.decode_chunk_blocks} does not divide "
                f"the block-table width ({self.blocks_per_seq} blocks per "
                "sequence); pick a divisor or 0 for the full-table gather"
            )

    # -- derived sizes -------------------------------------------------------

    @property
    def max_seq_len(self) -> int:
        """Longest sequence a lane can hold (prompt + generated)."""
        return self.max_prompt_len + self.max_new_tokens

    @property
    def blocks_per_seq(self) -> int:
        """Block-table width: logical blocks a worst-case sequence spans."""
        return self.blocks_for(self.max_seq_len)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # scratch block 0 is never allocated

    def blocks_for(self, n_tokens: int) -> int:
        from determined_tpu.serve.kv_cache import blocks_for_tokens

        return blocks_for_tokens(n_tokens, self.block_size)

    @classmethod
    def from_dict(cls, raw: Optional[Dict[str, Any]]) -> "ServeConfig":
        raw = dict(raw or {})
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            raise ValueError(f"unknown serve config keys: {sorted(unknown)}")
        return cls(**raw)
