from determined_tpu.common.context import (
    build_context,
    extract_context,
    read_detignore,
    ContextTooLargeError,
)

__all__ = [
    "build_context",
    "extract_context",
    "read_detignore",
    "ContextTooLargeError",
]
