"""Minimal RFC6455 websocket client + server primitives.

Used by the shell task (``exec/shell.py`` — PTY behind a websocket), the
CLI's ``shell open`` terminal bridge, and the devcluster tests that drive a
jupyter kernel through the master proxy.  The reference tunnels such
channels through Go's websocket stack + sshd (``master/internal/proxy/
proxy.go``, ``harness/determined/cli/tunnel.py``); here one small codec
serves both ends.

Scope: text/binary/ping/pong/close frames, client-side masking, server
handshake.  No extensions, no compression — none of our peers negotiate
them (the proxy forwards ``Sec-WebSocket-Extensions`` but jupyter/our tasks
run without permessage-deflate).
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    """Sec-WebSocket-Accept for a client's Sec-WebSocket-Key."""
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    """One complete (FIN) frame."""
    head = bytearray([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head.append(mask_bit | n)
    elif n < (1 << 16):
        head.append(mask_bit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        head += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


class WebSocket:
    """A connected websocket endpoint over a plain socket.

    ``client=True`` masks outgoing frames (RFC6455 §5.3 requires it of
    clients; servers must not mask).
    """

    def __init__(self, sock: socket.socket, client: bool) -> None:
        self.sock = sock
        self.client = client
        self._buf = b""
        self.closed = False
        # sends may come from multiple threads (a PTY pump thread plus the
        # receive loop's automatic PONG replies); frames must not interleave
        self._send_lock = threading.Lock()

    # -- send ----------------------------------------------------------------

    def send_text(self, text: str) -> None:
        self._send(OP_TEXT, text.encode())

    def send_binary(self, data: bytes) -> None:
        self._send(OP_BINARY, data)

    def send_close(self, code: int = 1000) -> None:
        try:
            self._send(OP_CLOSE, struct.pack(">H", code))
        except OSError:
            pass
        self.closed = True

    def _send(self, opcode: int, payload: bytes) -> None:
        frame = encode_frame(opcode, payload, mask=self.client)
        with self._send_lock:
            self.sock.sendall(frame)

    # -- receive -------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("websocket peer closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def recv_frame(self) -> Tuple[int, bytes]:
        """Next frame as (opcode, payload); reassembles fragmented messages."""
        opcode = None
        payload = b""
        while True:
            b1, b2 = self._read_exact(2)
            fin = b1 & 0x80
            op = b1 & 0x0F
            masked = b2 & 0x80
            n = b2 & 0x7F
            if n == 126:
                (n,) = struct.unpack(">H", self._read_exact(2))
            elif n == 127:
                (n,) = struct.unpack(">Q", self._read_exact(8))
            key = self._read_exact(4) if masked else None
            data = self._read_exact(n)
            if key:
                data = bytes(c ^ key[i % 4] for i, c in enumerate(data))
            if op in (OP_PING,):
                self._send(OP_PONG, data)
                continue
            if op in (OP_PONG,):
                continue
            if opcode is None:
                opcode = op
            payload += data
            if fin:
                return opcode, payload

    def recv_message(self) -> Tuple[int, bytes]:
        """Like recv_frame but answers pings and surfaces close frames."""
        op, data = self.recv_frame()
        if op == OP_CLOSE:
            self.closed = True
        return op, data

    def has_buffered_frame(self) -> bool:
        """True when a complete frame already sits in the internal buffer.

        Callers multiplexing on the raw socket (select/poll) must drain
        buffered frames first — one recv() can deliver several frames, and
        select would never fire for bytes already read.  Caveat: a buffered
        PING (which recv_message swallows) or a non-FIN fragment can still
        make the next recv_message block; our peers (shell PTY, jupyter)
        send unfragmented data frames.
        """
        buf = self._buf
        if len(buf) < 2:
            return False
        n = buf[1] & 0x7F
        off = 2
        if n == 126:
            if len(buf) < 4:
                return False
            (n,) = struct.unpack(">H", buf[2:4])
            off = 4
        elif n == 127:
            if len(buf) < 10:
                return False
            (n,) = struct.unpack(">Q", buf[2:10])
            off = 10
        if buf[1] & 0x80:
            off += 4
        return len(buf) >= off + n

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def connect(
    host: str,
    port: int,
    path: str,
    headers: Optional[Dict[str, str]] = None,
    timeout: float = 30.0,
    tls_ca: Optional[str] = None,
) -> WebSocket:
    """Client handshake; raises on a non-101 response.

    ``tls_ca``: connect over TLS (wss) verifying against the CA bundle —
    used when the master proxy serves HTTPS.
    """
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if tls_ca:
        import ssl

        ctx = ssl.create_default_context(cafile=tls_ca)
        sock = ctx.wrap_socket(sock, server_hostname=host)
    key = base64.b64encode(os.urandom(16)).decode()
    req = [
        f"GET {path} HTTP/1.1",
        f"Host: {host}:{port}",
        "Upgrade: websocket",
        "Connection: Upgrade",
        f"Sec-WebSocket-Key: {key}",
        "Sec-WebSocket-Version: 13",
    ]
    for k, v in (headers or {}).items():
        req.append(f"{k}: {v}")
    sock.sendall(("\r\n".join(req) + "\r\n\r\n").encode())

    resp = b""
    while b"\r\n\r\n" not in resp:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("connection closed during ws handshake")
        resp += chunk
    head, rest = resp.split(b"\r\n\r\n", 1)
    status_line = head.split(b"\r\n", 1)[0].decode()
    if " 101 " not in status_line + " ":
        raise ConnectionError(f"websocket handshake failed: {status_line}")
    expect = accept_key(key)
    if expect.encode() not in head:
        raise ConnectionError("bad Sec-WebSocket-Accept from server")
    ws = WebSocket(sock, client=True)
    ws._buf = rest
    return ws


def accept(sock: socket.socket, headers: Dict[str, str], leftover: bytes = b"") -> WebSocket:
    """Server-side handshake over an already-parsed HTTP upgrade request.

    ``headers`` must be lower-cased; ``leftover`` is any bytes the caller
    read past the request head.
    """
    key = headers.get("sec-websocket-key", "")
    if not key:
        raise ValueError("missing Sec-WebSocket-Key")
    resp = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(key)}\r\n\r\n"
    )
    sock.sendall(resp.encode())
    ws = WebSocket(sock, client=False)
    ws._buf = leftover
    return ws
