"""Experiment context directory: package user code for the cluster.

Reference: ``harness/determined/common/context.py`` (build/upload the
workdir tarball at submit) + ``harness/determined/common/detignore.py``
(exclusion patterns) + ``harness/determined/exec/prep_container.py:28-46``
(download/unpack in the task container).  TPU redesign: the tarball rides
inside the experiment-create request as base64 (one JSON protocol end to
end, no multipart), the master stores it on disk next to its journal, and
the trial process downloads and unpacks it before importing the entrypoint
(there is no container layer on a TPU VM).
"""

from __future__ import annotations

import fnmatch
import gzip
import io
import os
import tarfile
from typing import List

# always excluded, mirroring the reference's implicit excludes
DEFAULT_IGNORE = [
    ".git",
    "__pycache__",
    "*.pyc",
    ".detignore",
    ".pytest_cache",
]

MAX_CONTEXT_BYTES = 64 << 20  # request-body friendly cap (ref caps at ~95MB)

DETIGNORE_FILE = ".detignore"


class ContextTooLargeError(RuntimeError):
    pass


def read_detignore(root: str) -> List[str]:
    """Patterns from <root>/.detignore (gitignore-lite: fnmatch per line,
    '#' comments, blank lines skipped, trailing '/' matches directories)."""
    path = os.path.join(root, DETIGNORE_FILE)
    if not os.path.isfile(path):
        return []
    patterns = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            patterns.append(line)
    return patterns


def _ignored(rel: str, is_dir: bool, patterns: List[str]) -> bool:
    name = os.path.basename(rel)
    for pat in patterns:
        dir_only = pat.endswith("/")
        p = pat.rstrip("/")
        if dir_only and not is_dir:
            continue
        if fnmatch.fnmatch(rel, p) or fnmatch.fnmatch(name, p):
            return True
    return False


def build_context(root: str, max_size: int = MAX_CONTEXT_BYTES) -> bytes:
    """Deterministic tar.gz of the context directory, honoring .detignore."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        raise FileNotFoundError(f"context directory not found: {root}")
    patterns = DEFAULT_IGNORE + read_detignore(root)

    entries: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        rel_dir = "" if rel_dir == "." else rel_dir
        # prune ignored dirs in place so walk skips their subtrees
        dirnames[:] = sorted(
            d
            for d in dirnames
            if not _ignored(os.path.join(rel_dir, d) if rel_dir else d, True, patterns)
        )
        # walk(followlinks=False) lists dir-symlinks but never descends or
        # yields them as files.  In-tree links are archived as symlinks
        # (extraction re-links them); out-of-tree links can't survive
        # extraction on another host, so warn loudly instead of silently
        # dropping part of the user's code layout.
        for d in list(dirnames):
            full = os.path.join(dirpath, d)
            if os.path.islink(full):
                dirnames.remove(d)
                rel = os.path.join(rel_dir, d) if rel_dir else d
                target = os.path.realpath(full)
                if target == root or target.startswith(root + os.sep):
                    entries.append(rel)
                else:
                    import warnings

                    warnings.warn(
                        f"context: symlink {rel!r} -> {target!r} points outside "
                        f"the context directory and will NOT be shipped",
                        stacklevel=2,
                    )
        for fn in sorted(filenames):
            rel = os.path.join(rel_dir, fn) if rel_dir else fn
            if not _ignored(rel, False, patterns):
                entries.append(rel)

    buf = io.BytesIO()
    # mtime pinned for deterministic bytes (same tree -> same tarball)
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as gz:
        with tarfile.open(fileobj=gz, mode="w") as tar:
            for rel in entries:
                full = os.path.join(root, rel)
                info = tar.gettarinfo(full, arcname=rel)
                info.mtime = 0
                info.uid = info.gid = 0
                info.uname = info.gname = ""
                if info.isreg():
                    with open(full, "rb") as f:
                        tar.addfile(info, f)
                else:
                    tar.addfile(info)
    data = buf.getvalue()
    if len(data) > max_size:
        raise ContextTooLargeError(
            f"context tarball is {len(data)} bytes (cap {max_size}); "
            f"use {DETIGNORE_FILE} to exclude data/artifacts"
        )
    return data


def extract_context(data: bytes, dst: str) -> None:
    """Unpack a context tarball, refusing path traversal."""
    os.makedirs(dst, exist_ok=True)
    dst_real = os.path.realpath(dst)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        for member in tar.getmembers():
            target = os.path.realpath(os.path.join(dst_real, member.name))
            if not (target == dst_real or target.startswith(dst_real + os.sep)):
                raise RuntimeError(f"context entry escapes workdir: {member.name}")
            if member.issym() or member.islnk():
                link_target = os.path.realpath(
                    os.path.join(os.path.dirname(target), member.linkname)
                )
                if not link_target.startswith(dst_real + os.sep):
                    raise RuntimeError(
                        f"context link escapes workdir: {member.name} -> {member.linkname}"
                    )
        # "data" filter re-checks traversal/links/permissions kernel-side
        tar.extractall(dst_real, filter="data")
