"""Batch inference over a sharded dataset with checkpointed progress.

Reference: ``harness/determined/pytorch/experimental/_torch_batch_process.py``
(``TorchBatchProcessor``: each worker processes its dataset shard batch by
batch; progress is checkpointed so a preempted job resumes where it left
off).  TPU-first redesign: the processor's ``process_batch`` gets host
numpy batches from this process's shard — model calls inside it are
ordinary jitted functions, so the MXU path needs no special plumbing — and
progress/preemption run through the same Core API contexts as training
(dummy variants off-cluster).

Usage::

    class Embedder(inference.BatchProcessor):
        def setup(self):
            _, self.trainer = train.load_trial_from_checkpoint(path)
        def process_batch(self, batch, batch_idx):
            out = my_jitted_embed(self.trainer.state.params, batch["x"])
            np.save(self.output_dir / f"part-{batch_idx}.npy", out)

    inference.run_batch_inference(Embedder, dataset, batch_size=256)
"""

from __future__ import annotations

import abc
import logging
from typing import Any, Dict, Optional, Type

import numpy as np

from determined_tpu.data._loader import DataLoader

logger = logging.getLogger("determined_tpu.inference")


class BatchProcessor(abc.ABC):
    """User hook object; one instance per worker process."""

    def __init__(self, core_context: Any, rank: int, size: int) -> None:
        self.core = core_context
        self.rank = rank
        self.size = size
        self.setup()

    def setup(self) -> None:
        """Build models/outputs; runs once before the first batch."""

    @abc.abstractmethod
    def process_batch(self, batch: Dict[str, np.ndarray], batch_idx: int) -> None:
        """Handle one host batch from this worker's shard."""

    def on_finish(self) -> None:
        """Runs after the shard is exhausted (chief and workers)."""


def run_batch_inference(
    processor_cls: Type[BatchProcessor],
    dataset: Any,
    batch_size: int,
    core_context: Optional[Any] = None,
    checkpoint_interval: int = 50,
) -> int:
    """Process the dataset once; returns batches processed by this worker.

    - the dataset shards over the job's processes (same reproducible
      sampler as training);
    - every ``checkpoint_interval`` batches the chief records progress via
      ``core.checkpoint`` metadata, and the preemption flag is polled —
      a preempted run resumes from the recorded batch index.
    """
    from determined_tpu import core as core_mod

    ctx = core_context or core_mod.init()
    dist = ctx.distributed
    loader = DataLoader(
        dataset,
        batch_size,
        shuffle=False,
        num_shards=dist.size,
        shard_rank=dist.rank,
    )

    start_batch = 0
    info = getattr(ctx, "info", None)
    latest = getattr(info, "latest_checkpoint", None) if info else None
    if latest:
        with ctx.checkpoint.restore_path(latest) as path:
            import json
            import os

            marker = os.path.join(path, "inference_progress.json")
            if os.path.exists(marker):
                with open(marker) as f:
                    start_batch = int(json.load(f)["batches_done"])
        logger.info("resuming batch inference at batch %d", start_batch)

    from determined_tpu.data._loader import _fetch

    proc = processor_cls(ctx, dist.rank, dist.size)
    done = 0
    batches = loader.sampler.epoch_batches(0)
    total = loader.sampler.batches_per_epoch
    for idx in range(start_batch, total):
        batch = _fetch(dataset, batches[idx])
        proc.process_batch(batch, idx)
        done += 1
        if done % checkpoint_interval == 0:
            _record_progress(ctx, dist, idx + 1)
            if ctx.preempt.should_preempt():
                logger.info("preempted at batch %d; progress checkpointed", idx + 1)
                # should_preempt() IS the exchange (allgather of per-rank
                # flags), so every rank returns from the same batch index
                return done  # dtpu: lint-ok[conditional-collective-escape]
    # Final marker BEFORE on_finish: progress was only recorded every
    # checkpoint_interval, so a rank preempted after its last batch but
    # before on_finish would replay the whole tail on resume.  Skipped
    # when the interval already recorded it (no redundant checkpoint) or
    # when this worker processed nothing.
    if done and done % checkpoint_interval != 0:
        _record_progress(ctx, dist, total)
    proc.on_finish()
    return done


def _record_progress(ctx: Any, dist: Any, batches_done: int) -> None:
    import json
    import os

    if dist.is_chief:
        with ctx.checkpoint.store_path({"batches_done": batches_done}) as (path, _sid):
            with open(os.path.join(path, "inference_progress.json"), "w") as f:
                json.dump({"batches_done": batches_done}, f)
    if dist.size > 1:
        dist.barrier()
