"""Shared plumbing for the HuggingFace Flax trial families.

One holder pattern serves every HF family (BERT, GPT-2, ...): it wraps
the raw flax ``.module`` so ``build_model`` returns a single object with
the config attached, and implements the offline ``pretrained_dir``
contract — a local ``save_pretrained`` directory's weights become the
initial params (returned by ``init``), so the trial is a true fine-tune
with no network touched.  Subclasses supply the transformers model class
and the positional forward arguments their architecture expects.
"""

from __future__ import annotations

from typing import Any, Tuple


class HFModuleHolder:
    """Base holder; subclasses define ``_model_cls`` and ``_forward_args``."""

    def __init__(self, config, seed: int, pretrained_dir: str = "") -> None:
        model_cls = self._model_cls()
        self.config = config
        self._pretrained = None
        if pretrained_dir:
            loaded = model_cls.from_pretrained(
                pretrained_dir, config=config, local_files_only=True
            )
            self._pretrained = {"params": loaded.params}
            self.module = loaded.module
        else:
            self.module = model_cls(config, seed=seed, _do_init=False).module

    @classmethod
    def _model_cls(cls):  # pragma: no cover - abstract
        raise NotImplementedError

    def _forward_args(self, input_ids) -> Tuple[Any, ...]:  # pragma: no cover
        raise NotImplementedError

    def init(self, rng, input_ids):
        if self._pretrained is not None:
            return self._pretrained
        return self.module.init(
            rng, *self._forward_args(input_ids), deterministic=True
        )

    def apply(self, params, input_ids, deterministic=True, rngs=None):
        return self.module.apply(
            params,
            *self._forward_args(input_ids),
            deterministic=deterministic,
            rngs=rngs,
        )
