"""Mixture-of-Experts layer with expert parallelism, TPU-first.

The reference has NO MoE/expert-parallel code (SURVEY §2.10: absent —
DeepSpeed passthrough at most); this is a capability the TPU build adds.
Design follows the GShard/Switch pjit formulation rather than explicit
all-to-all plumbing: expert weights are stacked ``[experts, ...]`` tensors
whose leading dim carries the ``"expert"`` logical axis, and token routing
is expressed as dense dispatch/combine einsums — under ``pjit`` over a mesh
with an ``expert`` axis, XLA partitions the expert dim and inserts the
all-to-all collectives itself (the "let the compiler place collectives"
recipe).  Top-2 gating with capacity limiting and the standard
load-balancing auxiliary loss (Switch Transformer eq. 4).

Shapes (g = tokens per group, e = experts, c = capacity, d/f = model/ff):
  gates      [g, e]      softmax router probabilities
  dispatch   [g, e, c]   0/1 token->expert-slot assignment
  combine    [g, e, c]   dispatch * gate prob (weighted un-routing)
  x          [g, d]  ->  expert inputs  [e, c, d]   (einsum with dispatch)
  expert ffn [e, c, d] @ w1[e, d, f] -> silu -> @ w2[e, f, d]
  y          [g, d]      (einsum with combine)
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from determined_tpu.parallel._compat import axis_size


def _top2_dispatch(
    gates: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build dispatch/combine tensors for top-2 routing with capacity.

    Tokens beyond an expert's capacity are dropped (standard GShard
    behavior); the combine weights renormalize over the surviving routes.
    Returns (dispatch [g,e,c], combine [g,e,c], aux_loss scalar).
    """
    g, e = gates.shape
    # top-1 and top-2 expert per token
    idx1 = jnp.argmax(gates, axis=-1)                          # [g]
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)         # [g, e]
    gates2 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # load-balancing aux loss (Switch eq. 4): e * sum_e(fraction_tokens_e
    # * mean_prob_e) — equals 1 at perfect balance regardless of e, so the
    # aux weight means the same thing at any expert count
    density = mask1.mean(axis=0)                               # [e]
    density_proxy = gates.mean(axis=0)                         # [e]
    aux = (density * density_proxy).sum() * e

    # position of each token in its expert's queue (top-1 first)
    pos1 = (jnp.cumsum(mask1, axis=0) - 1.0) * mask1           # [g, e]
    used1 = jnp.sum(mask1, axis=0, keepdims=True)              # [1, e]
    pos2 = ((jnp.cumsum(mask2, axis=0) - 1.0) + used1) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    p1 = (gates * keep1).sum(axis=-1)                          # [g]
    p2 = (gates * keep2).sum(axis=-1)
    denom = jnp.maximum(p1 + p2, 1e-9)
    w1 = p1 / denom
    w2 = p2 / denom

    def slots(keep, pos):
        slot = jax.nn.one_hot(
            (pos * keep).sum(axis=-1).astype(jnp.int32), capacity,
            dtype=gates.dtype,
        )                                                       # [g, c]
        return keep[:, :, None] * slot[:, None, :]              # [g, e, c]

    d1, d2 = slots(keep1, pos1), slots(keep2, pos2)
    dispatch = d1 + d2
    combine = d1 * w1[:, None, None] + d2 * w2[:, None, None]
    return dispatch, combine, aux


class MoE(nn.Module):
    """Top-2 expert-parallel SwiGLU FFN (drop-in for a dense MLP block).

    Tokens route within fixed-size GROUPS (GShard's formulation): dispatch
    and combine are ``[groups, group_size, e, c]`` with ``c ~
    2*group_size/e``, so their size is linear in the token count —
    grouping capacity over the whole flattened batch would make them
    quadratic and OOM real configs (64k tokens x 8 experts would need
    ~1e10-element dispatch tensors).
    """

    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    group_size: int = 4096
    dtype: Any = jnp.bfloat16
    partition: bool = True  # False under manual-SPMD pipeline stages
    # Manual-SPMD expert parallelism (inside pipeline-stage shard_map):
    # expert weights arrive sharded over this axis (only e/n local experts
    # per device); routing/gating stays replicated, each device computes
    # the FFN for ITS experts against the full token set, and the combine
    # is a psum over the axis — the intra-stage expert "all-to-all".
    expert_axis_name: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """[batch, seq, d] -> ([batch, seq, d], aux_loss)."""
        from determined_tpu.models.transformer import _maybe_partition

        b, s, d = x.shape
        g = b * s
        e = self.num_experts
        # pad up to a group multiple rather than shrinking groups: a
        # divisor fallback can degenerate to tiny groups (prime token
        # counts), collapsing capacity and dropping every top-2 route.
        # Padded (zero) tokens route uniformly and consume at most the pad
        # fraction of capacity; their outputs are sliced away.
        grp = min(self.group_size, g)
        pad = (-g) % grp
        n_groups = (g + pad) // grp
        capacity = max(int(self.capacity_factor * grp * 2 / e), 1)

        xf = x.reshape(g, d)
        if pad:
            xf = jnp.concatenate([xf, jnp.zeros((pad, d), x.dtype)], axis=0)
        xg = xf.reshape(n_groups, grp, d)
        router = self.param(
            "router",
            _maybe_partition(
                self.partition, nn.initializers.lecun_normal(), ("embed", "expert")
            ),
            (d, e),
            jnp.float32,
        )
        # routing decisions in f32: bf16 softmax ties misroute tokens
        gates = jax.nn.softmax(
            jnp.einsum("ngd,de->nge", xg.astype(jnp.float32), router)
        )
        dispatch, combine, aux = jax.vmap(
            lambda gate: _top2_dispatch(gate, capacity)
        )(gates)
        aux = aux.mean()

        # under manual SPMD the params hold only this device's experts
        e_param = e
        my_expert0 = None
        if self.expert_axis_name is not None:
            n_exp = axis_size(self.expert_axis_name)
            if e % n_exp:
                raise ValueError(f"num_experts={e} not divisible by axis {n_exp}")
            e_param = e // n_exp
            my_expert0 = jax.lax.axis_index(self.expert_axis_name) * e_param

        def expert_param(name, shape, logical):
            return self.param(
                name,
                _maybe_partition(
                    self.partition, nn.initializers.lecun_normal(), logical
                ),
                shape,
                jnp.float32,
            )

        w_in = expert_param("w_in", (e_param, d, self.d_ff), ("expert", "embed", "mlp"))
        w_gate = expert_param("w_gate", (e_param, d, self.d_ff), ("expert", "embed", "mlp"))
        w_out = expert_param("w_out", (e_param, self.d_ff, d), ("expert", "mlp", "embed"))

        if my_expert0 is not None:
            # keep only the dispatch/combine slices for MY experts; the
            # cross-device combine is the psum below
            dispatch = jax.lax.dynamic_slice_in_dim(dispatch, my_expert0, e_param, axis=2)
            combine = jax.lax.dynamic_slice_in_dim(combine, my_expert0, e_param, axis=2)

        cd = self.dtype
        # dispatch: [n,g,e,c] x [n,g,d] -> [n,e,c,d]; under an
        # "expert"-sharded mesh axis XLA turns these einsums into the
        # all-to-alls
        expert_in = jnp.einsum(
            "ngec,ngd->necd", dispatch.astype(cd), xg.astype(cd)
        )
        h = jnp.einsum("necd,edf->necf", expert_in, w_in.astype(cd))
        gate = jnp.einsum("necd,edf->necf", expert_in, w_gate.astype(cd))
        h = nn.silu(gate) * h
        expert_out = jnp.einsum("necf,efd->necd", h, w_out.astype(cd))
        y = jnp.einsum("ngec,necd->ngd", combine.astype(cd), expert_out)
        if self.expert_axis_name is not None:
            y = jax.lax.psum(y, self.expert_axis_name)
        y = y.reshape(n_groups * grp, d)[:g]
        return y.reshape(b, s, d), aux.astype(jnp.float32)
