"""Model zoo: MNIST tutorials, flagship transformer LM, DDPM diffusion,
HF Flax fine-tune families (BERT, GPT-2 — imported lazily from their
modules to keep transformers optional)."""

from determined_tpu.models.diffusion import DiffusionTrial, UNet, ddpm_sample
from determined_tpu.models.mnist import MnistCNN, MnistMLP, MnistTrial
from determined_tpu.models.transformer import (
    LMTrial,
    TransformerConfig,
    TransformerLM,
)

__all__ = [
    "DiffusionTrial",
    "UNet",
    "ddpm_sample",
    "MnistCNN",
    "MnistMLP",
    "MnistTrial",
    "LMTrial",
    "TransformerConfig",
    "TransformerLM",
]
