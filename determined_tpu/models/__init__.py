"""Model zoo: MNIST tutorials + flagship transformer LM."""

from determined_tpu.models.mnist import MnistCNN, MnistMLP, MnistTrial
from determined_tpu.models.transformer import (
    LMTrial,
    TransformerConfig,
    TransformerLM,
)

__all__ = [
    "MnistCNN",
    "MnistMLP",
    "MnistTrial",
    "LMTrial",
    "TransformerConfig",
    "TransformerLM",
]
