"""Flagship model: decoder-only transformer LM, TPU-first.

The reference platform ships no model internals (its deepest model hooks
are DeepSpeed pipeline/MPU passthrough, ``deepspeed/_mpu.py``).  This is
the framework's flagship: one module that runs DP / FSDP / TP / SP by
MeshConfig alone, with:

- logical-axis partitioning on every kernel (embed/heads/kv/mlp/vocab),
  resolved by LogicalAxisRules -> XLA inserts the collectives;
- activation sharding constraints (batch over dp/fsdp, seq over sp);
- rotary position embeddings, GQA, RMSNorm, SwiGLU;
- attention dispatch: ring attention when the mesh has a "seq" axis,
  Pallas flash attention on TPU otherwise, reference for tiny seqs;
- bf16 compute with f32 params, per-block remat for long-context memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from determined_tpu.data import DataLoader, SyntheticDataset
from determined_tpu.ops.attention import (
    NEG_INF,
    _repeat_kv,
    dot_product_attention,
    reference_attention,
)
from determined_tpu.ops.ring_attention import ring_attention
from determined_tpu.parallel.mesh import MeshAxes
from determined_tpu.parallel.sharding import with_sharding_constraint
from determined_tpu.train._trial import JaxTrial


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None          # None -> n_heads (MHA)
    d_ff: Optional[int] = None                # None -> 4 * d_model
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16                 # activation/compute dtype
    attention_impl: str = "auto"              # auto|reference|flash|ring
    remat: bool = False
    rope_theta: float = 10000.0
    # MoE (models/moe.py): every moe_every-th block swaps its dense MLP
    # for top-2 expert-parallel experts; 0 = dense everywhere
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Quantized matmul arithmetic (train/_quant.py): none|int8|fp8 routes
    # every dense/attention projection matmul (and the logits-path
    # lm_head) through per-channel dynamically-scaled reduced-precision
    # arithmetic with fp32 master weights.  The param tree is untouched
    # (a flax dot_general injection), so checkpoints and sharding specs
    # are byte-compatible across modes; composes with pipe (stage blocks
    # inherit the config).  The fused-CE lm_head contraction keeps its
    # own bf16 kernel.
    quantized_matmul: str = "none"
    # False under manual-SPMD pipeline stages: logical param annotations
    # are meaningless (and invalid) inside shard_map, where placement is
    # explicit
    partition_params: bool = True
    # Manual-SPMD axis names, set ONLY inside pipeline stages (shard_map):
    # seq_axis_name routes attention through ring_attention_local over that
    # axis (with globally-offset rope positions); expert_axis_name makes
    # MoE blocks run local-expert compute + psum-combine over that axis.
    seq_axis_name: Optional[str] = None
    expert_axis_name: Optional[str] = None

    def __post_init__(self):
        if self.moe_experts > 0 and self.moe_every < 1:
            raise ValueError(
                "moe_every must be >= 1 when moe_experts > 0 "
                f"(got moe_every={self.moe_every})"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings on [b, h, s, d]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [s, d/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rx1 = x1 * cos - x2 * sin
    rx2 = x1 * sin + x2 * cos
    return jnp.stack([rx1, rx2], axis=-1).reshape(x.shape).astype(x.dtype)


def _maybe_partition(partition: bool, init, names):
    """with_partitioning when annotations apply; plain init under manual
    SPMD (pipeline stages inside shard_map)."""
    return nn.with_partitioning(init, names) if partition else init


class RMSNorm(nn.Module):
    eps: float = 1e-6
    partition: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "scale",
            _maybe_partition(self.partition, nn.initializers.ones, ("embed",)),
            (x.shape[-1],),
        )
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)) * scale.astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None  # jax.sharding.Mesh when ring attention is in play

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        from determined_tpu.train._quant import make_dot_general

        qdg = make_dot_general(cfg.quantized_matmul)
        dense = lambda feats, logical, name: nn.DenseGeneral(  # noqa: E731
            feats,
            axis=-1,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            dot_general=qdg,
            kernel_init=_maybe_partition(
                cfg.partition_params, nn.initializers.lecun_normal(), logical
            ),
            name=name,
        )
        q = dense((cfg.n_heads, hd), ("embed", "heads", "head_dim"), "wq")(x)
        k = dense((cfg.kv_heads, hd), ("embed", "kv", "head_dim"), "wk")(x)
        v = dense((cfg.kv_heads, hd), ("embed", "kv", "head_dim"), "wv")(x)
        # [b, s, h, d] -> [b, h, s, d]
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        positions = jnp.arange(s)
        if cfg.seq_axis_name is not None:
            # manual SPMD inside a pipeline stage: s is the LOCAL shard
            # length; rope positions are global (contiguous assignment)
            positions = positions + jax.lax.axis_index(cfg.seq_axis_name) * s
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

        impl = cfg.attention_impl
        use_ring = (
            impl == "ring"
            or (
                impl == "auto"
                and self.mesh is not None
                and self.mesh.shape.get(MeshAxes.SEQUENCE, 1) > 1
            )
        )
        if cfg.seq_axis_name is not None:
            # already inside shard_map over the seq axis: run the ring on
            # local shards (zigzag-balanced for causal)
            from determined_tpu.ops.ring_attention import ring_attention_local

            out = ring_attention_local(
                q, k, v, axis_name=cfg.seq_axis_name, causal=True
            )
        elif use_ring:
            if self.mesh is None:
                raise ValueError("ring attention requires the mesh")
            out = ring_attention(q, k, v, self.mesh, causal=True)
        else:
            out = dot_product_attention(q, k, v, causal=True, impl=impl)
        out = out.transpose(0, 2, 1, 3)  # [b, s, h, d]
        out = nn.DenseGeneral(
            cfg.d_model,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            dot_general=qdg,
            kernel_init=_maybe_partition(
                cfg.partition_params,
                nn.initializers.lecun_normal(),
                ("heads", "head_dim", "embed"),
            ),
            name="wo",
        )(out)
        return out


class MLP(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        from determined_tpu.train._quant import make_dot_general

        qdg = make_dot_general(cfg.quantized_matmul)
        dense = lambda feats, logical, name: nn.Dense(  # noqa: E731
            feats,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            dot_general=qdg,
            kernel_init=_maybe_partition(
                cfg.partition_params, nn.initializers.lecun_normal(), logical
            ),
            name=name,
        )
        gate = dense(cfg.ff_dim, ("embed", "mlp"), "w_gate")(x)
        up = dense(cfg.ff_dim, ("embed", "mlp"), "w_up")(x)
        h = nn.silu(gate) * up
        if cfg.partition_params:
            h = with_sharding_constraint(h, ("batch", "length", "mlp"), mesh=self.mesh)
        return dense(cfg.d_model, ("mlp", "embed"), "w_down")(h)


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None
    use_moe: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = x + Attention(self.cfg, self.mesh, name="attn")(
            RMSNorm(partition=self.cfg.partition_params, name="ln1")(x)
        )
        if self.use_moe:
            from determined_tpu.models.moe import MoE

            y, aux = MoE(
                num_experts=self.cfg.moe_experts,
                d_ff=self.cfg.ff_dim,
                capacity_factor=self.cfg.moe_capacity_factor,
                dtype=self.cfg.dtype,
                partition=self.cfg.partition_params,
                expert_axis_name=self.cfg.expert_axis_name,
                name="moe",
            )(RMSNorm(partition=self.cfg.partition_params, name="ln2")(x))
            x = x + y
        else:
            x = x + MLP(self.cfg, self.mesh, name="mlp")(
                RMSNorm(partition=self.cfg.partition_params, name="ln2")(x)
            )
            aux = jnp.zeros((), jnp.float32)
        if self.cfg.partition_params:
            x = with_sharding_constraint(x, ("batch", "length", "embed"), mesh=self.mesh)
        return x, aux


class TransformerLM(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        return_hidden: bool = False,
        return_aux: bool = False,
    ) -> Any:
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            embedding_init=_maybe_partition(
                cfg.partition_params,
                nn.initializers.normal(stddev=0.02),
                ("vocab", "embed"),
            ),
            name="embed",
        )
        x = embed(tokens)
        if cfg.partition_params:
            x = with_sharding_constraint(x, ("batch", "length", "embed"), mesh=self.mesh)
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, prevent_cse=False)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            use_moe = (
                cfg.moe_experts > 0 and (i % cfg.moe_every) == cfg.moe_every - 1
            )
            x, aux = block_cls(cfg, self.mesh, use_moe, name=f"block_{i}")(x)
            aux_total = aux_total + aux
        x = RMSNorm(partition=cfg.partition_params, name="ln_f")(x)
        from determined_tpu.train._quant import make_dot_general

        lm_head = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            dot_general=make_dot_general(cfg.quantized_matmul),
            kernel_init=_maybe_partition(
                cfg.partition_params, nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )
        if return_hidden:
            # fused-CE path: the caller contracts x with lm_head's kernel
            # chunk-by-chunk (ops/cross_entropy.py) so [b, s, vocab] logits
            # never hit HBM.  Init always takes the logits path, so the
            # param tree includes lm_head either way.
            return (x, aux_total) if return_aux else x
        out = lm_head(x).astype(jnp.float32)
        return (out, aux_total) if return_aux else out


def split_pipeline_params(
    boxed_params: Any, n_stages: int, virtual_stages: int = 1
) -> Dict[str, Any]:
    """Restructure a plain ``TransformerLM`` param tree for pipeline stages.

    Input: the tree from ``TransformerLM.init`` (possibly flax-``Partitioned``
    boxed).  Output: ``{"outer": <embed/ln_f/lm_head, boxes kept>, "blocks":
    {"layer_j": <layer j of every chunk stacked on a leading [P, ...] dim>}}``
    for j in [0, layers_per_chunk) — the per-layer dict (instead of an extra
    stacked lps dim) lets DENSE and MOE layers coexist in one chunk: layer j
    must have the same param structure across chunks (requiring the MoE
    period to divide layers-per-chunk), but different j's may differ.

    ``virtual_stages`` > 1 (the circular-interleaved schedule) splits the
    stack into P*V chunks and stacks leaves as ``[P, V, ...]`` —
    ``[p, v]`` holds chunk ``v*P + p``, i.e. pipe rank p's V NON-adjacent
    layer blocks (``parallel/pipeline.py`` ``stack_chunk_params`` layout).

    Because the stacked leaves are built from the SAME initialized values as
    the flat ``block_i`` subtrees, a pipe>1 trial initializes identically to
    pipe=1 — the basis of the loss-parity tests.
    """
    from flax.core import meta as flax_meta

    from determined_tpu.config.experiment import InvalidExperimentConfig

    tree = dict(boxed_params["params"])
    block_keys = sorted(
        (k for k in tree if k.startswith("block_")), key=lambda k: int(k.split("_")[1])
    )
    n_layers = len(block_keys)
    chunks_total = n_stages * virtual_stages
    if n_layers == 0 or n_layers % chunks_total:
        raise InvalidExperimentConfig(
            f"n_layers={n_layers} not divisible into {chunks_total} pipeline "
            f"chunks (pipe={n_stages} x virtual_stages={virtual_stages})"
        )
    lpc = n_layers // chunks_total
    blocks = [flax_meta.unbox(tree.pop(k)) for k in block_keys]
    stacked = {}
    for j in range(lpc):
        # chunk c covers layers [c*lpc, (c+1)*lpc); chunk order is the
        # order the microbatch traverses them
        layer_j = [blocks[c * lpc + j] for c in range(chunks_total)]
        structures = {jax.tree.structure(t) for t in layer_j}
        if len(structures) > 1:
            raise InvalidExperimentConfig(
                f"layer {j} differs in structure across pipeline chunks "
                "(is the MoE period a divisor of layers-per-chunk?)"
            )
        if virtual_stages == 1:
            stacked[f"layer_{j}"] = jax.tree.map(
                lambda *ls: jnp.stack(ls), *layer_j
            )
        else:
            stacked[f"layer_{j}"] = jax.tree.map(
                lambda *ls: jnp.stack(
                    [
                        jnp.stack(
                            [ls[v * n_stages + p] for v in range(virtual_stages)]
                        )
                        for p in range(n_stages)
                    ]
                ),
                *layer_j,
            )
    outer = {"params": tree}
    extra = {k: v for k, v in boxed_params.items() if k != "params"}
    if extra:
        outer.update(extra)
    return {"outer": outer, "blocks": stacked}


def pipeline_forward(
    cfg: TransformerConfig,
    mesh: Any,
    params: Dict[str, Any],
    tokens: jax.Array,
    num_microbatches: int,
    return_hidden: bool = False,
    rules: Any = None,
    return_aux: bool = False,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> Any:
    """Forward pass with the transformer blocks pipelined over ``pipe``.

    ``params`` is the ``split_pipeline_params`` layout.  Embed / final norm /
    lm_head run as ordinary SPMD computation outside the pipeline (sharded by
    their logical annotations); only the block stack rides the microbatch
    schedule (``parallel/pipeline.py`` — gpipe, 1f1b, or circular
    interleaved per ``schedule``/``virtual_stages``).  Stage block params
    are sharded over ``pipe`` (expert weights additionally over ``expert``)
    inside the schedule's ``shard_map``; the batch stays sharded over
    data/fsdp and the sequence over ``seq`` — ring attention runs inside
    each stage over the seq axis, and MoE combine psums over the expert
    axis intra-stage.  (FSDP sharding of block *params* does not compose
    yet.)  The reference's DeepSpeed grid composes PP only with DP/TP
    (``deepspeed/_mpu.py:9-50``).
    """
    from flax.core import meta as flax_meta

    from determined_tpu.parallel.pipeline import pipeline_apply

    outer = flax_meta.unbox(params["outer"])["params"]
    blocks = params["blocks"]
    lps = len(blocks)
    layer_keys = [f"layer_{j}" for j in range(lps)]
    has_moe = [isinstance(blocks[k], dict) and "moe" in blocks[k] for k in layer_keys]

    seq_n = mesh.shape.get(MeshAxes.SEQUENCE, 1) if mesh is not None else 1
    exp_n = mesh.shape.get(MeshAxes.EXPERT, 1) if mesh is not None else 1
    if exp_n > 1 and any(has_moe) and cfg.moe_experts % exp_n:
        raise ValueError(
            f"moe_experts={cfg.moe_experts} not divisible by expert axis {exp_n}"
        )

    emb = nn.Embed(
        cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32
    )
    x = emb.apply({"params": outer["embed"]}, tokens)
    x = with_sharding_constraint(x, ("batch", "length", "embed"), mesh=mesh, rules=rules)

    stage_cfg = dataclasses.replace(
        cfg,
        partition_params=False,
        attention_impl="auto" if cfg.attention_impl == "ring" else cfg.attention_impl,
        seq_axis_name=MeshAxes.SEQUENCE if seq_n > 1 else None,
        expert_axis_name=MeshAxes.EXPERT if exp_n > 1 else None,
    )

    def make_block_step(use_moe: bool):
        blk = Block(stage_cfg, use_moe=use_moe)

        def block_step(p, h):
            return blk.apply({"params": p}, h)

        if cfg.remat:
            block_step = jax.checkpoint(block_step, prevent_cse=False)
        return block_step

    steps = [make_block_step(m) for m in has_moe]
    want_aux = any(has_moe)

    def stage_fn(stage_params, h):
        aux = jnp.zeros((), jnp.float32)
        for j, key in enumerate(layer_keys):
            h, a = steps[j](stage_params[key], h)
            aux = aux + a
        return (h, aux) if want_aux else h

    out = pipeline_apply(
        stage_fn, blocks, x, mesh, num_microbatches, with_aux=want_aux,
        schedule=schedule, virtual_stages=virtual_stages,
    )
    x, aux = out if want_aux else (out, jnp.zeros((), jnp.float32))
    x = RMSNorm(partition=False).apply({"params": outer["ln_f"]}, x)
    if return_hidden:
        return (x, aux) if return_aux else x
    from determined_tpu.train._quant import make_dot_general

    head = nn.Dense(
        cfg.vocab_size, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
        dot_general=make_dot_general(cfg.quantized_matmul),
    )
    logits = head.apply({"params": outer["lm_head"]}, x).astype(jnp.float32)
    return (logits, aux) if return_aux else logits


# ---------------------------------------------------------------------------
# KV-cache decode path (online serving: determined_tpu/serve)
# ---------------------------------------------------------------------------
#
# Training/eval run the full-sequence forward above; serving needs the
# autoregressive form: prefill the prompt once, then one-token decode steps
# reading/writing a **paged** KV cache (vLLM's PagedAttention layout, Kwon
# et al., SOSP '23).  The cache is a pool of fixed-size blocks
# ``[n_layers, num_blocks, block_size, kv_heads, head_dim]``; each sequence
# owns a *block table* mapping its logical block index to a physical block
# id.  Everything below is a pure function over the UNBOXED param tree that
# ``TransformerLM.init`` produces (the ``["params"]`` subtree), so the
# serve engine can jit prefill/decode with static shapes — batch lanes,
# table width, and prompt padding are fixed by ServeConfig, and the decode
# step traces exactly once no matter how request lengths mix (guarded by
# the RetraceSentinel in ``serve/engine.py``).
#
# Physical block 0 is a scratch block the allocator never hands out:
# padded prefill positions and inactive decode lanes write there, keeping
# the scatter shape static without masking arithmetic inside the kernel.


def kv_cache_shape(
    cfg: TransformerConfig, num_blocks: int, block_size: int
) -> Tuple[int, ...]:
    return (cfg.n_layers, num_blocks, block_size, cfg.kv_heads, cfg.head_dim)


def init_kv_cache(
    cfg: TransformerConfig, num_blocks: int, block_size: int
) -> Dict[str, jax.Array]:
    """Zeroed paged K/V pool in the model's compute dtype (keys are stored
    post-rope, i.e. exactly what attention consumes)."""
    shape = kv_cache_shape(cfg, num_blocks, block_size)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _rms_apply(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with the exact numerics of the ``RMSNorm`` module."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale.astype(x.dtype)


def _attn_proj(
    p: Dict[str, Any], x: jax.Array, dtype: Any
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """q/k/v projections as ``Attention`` computes them, to [b, heads, s, d]."""
    q = jnp.einsum("bsd,dhk->bhsk", x, p["wq"]["kernel"].astype(dtype))
    k = jnp.einsum("bsd,dhk->bhsk", x, p["wk"]["kernel"].astype(dtype))
    v = jnp.einsum("bsd,dhk->bhsk", x, p["wv"]["kernel"].astype(dtype))
    return q, k, v


def _mlp_apply(p: Dict[str, Any], x: jax.Array, dtype: Any) -> jax.Array:
    gate = x @ p["w_gate"]["kernel"].astype(dtype)
    up = x @ p["w_up"]["kernel"].astype(dtype)
    return (nn.silu(gate) * up) @ p["w_down"]["kernel"].astype(dtype)


def _rope_batched(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings on [b, h, 1, d] with a per-sequence position [b]
    (the decode step: every lane sits at its own offset)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [b, d/2]
    cos = jnp.cos(angles)[:, None, None, :]
    sin = jnp.sin(angles)[:, None, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rx1 = x1 * cos - x2 * sin
    rx2 = x1 * sin + x2 * cos
    return jnp.stack([rx1, rx2], axis=-1).reshape(x.shape).astype(x.dtype)


def _check_decodable(cfg: TransformerConfig) -> None:
    if cfg.moe_experts > 0:
        raise ValueError("KV-cache serving does not support MoE configs yet")
    if cfg.seq_axis_name is not None or cfg.expert_axis_name is not None:
        raise ValueError("KV-cache serving runs outside pipeline stages")


def transformer_prefill(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jax.Array,
    prompt_lens: jax.Array,
    block_tables: jax.Array,
    cache: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Full-prompt forward that also populates the paged cache.

    ``tokens`` [B, S] is the prompt padded to a fixed S (one trace);
    ``prompt_lens`` [B] the real lengths; ``block_tables`` [B, T] each
    lane's physical block ids.  Returns (logits [B, S, vocab] f32, cache).
    Logits at positions >= prompt_len are computed over padding — callers
    sample at ``prompt_len - 1``.  Causality makes positions < prompt_len
    match the full-sequence forward exactly (padding sits strictly after
    them), which is what the parity tests in tests/test_transformer.py pin.
    """
    _check_decodable(cfg)
    block_size = cache["k"].shape[2]
    b, s = tokens.shape
    dt = cfg.dtype
    x = jnp.take(params["embed"]["embedding"].astype(dt), tokens, axis=0)
    positions = jnp.arange(s)
    # physical destination of every (lane, position): padded tail -> scratch
    phys = jnp.where(
        positions[None, :] < prompt_lens[:, None],
        jnp.take_along_axis(
            block_tables, jnp.broadcast_to(positions[None, :] // block_size, (b, s)),
            axis=1,
        ),
        0,
    )
    slots = jnp.broadcast_to((positions % block_size)[None, :], (b, s))
    k_cache, v_cache = cache["k"], cache["v"]
    for i in range(cfg.n_layers):
        blk = params[f"block_{i}"]
        h = _rms_apply(x, blk["ln1"]["scale"])
        q, k, v = _attn_proj(blk["attn"], h, dt)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        k_cache = k_cache.at[i, phys, slots].set(k.transpose(0, 2, 1, 3))
        v_cache = v_cache.at[i, phys, slots].set(v.transpose(0, 2, 1, 3))
        att = reference_attention(q, k, v, causal=True)
        att = att.transpose(0, 2, 1, 3)  # [b, s, h, hd]
        x = x + jnp.einsum(
            "bshk,hkD->bsD", att, blk["attn"]["wo"]["kernel"].astype(dt)
        )
        x = x + _mlp_apply(blk["mlp"], _rms_apply(x, blk["ln2"]["scale"]), dt)
    x = _rms_apply(x, params["ln_f"]["scale"])
    logits = (x @ params["lm_head"]["kernel"].astype(dt)).astype(jnp.float32)
    return logits, {"k": k_cache, "v": v_cache}


def _paged_attention_chunked(
    q: jax.Array,
    k_cache_i: jax.Array,
    v_cache_i: jax.Array,
    block_tables: jax.Array,
    pos: jax.Array,
    active: jax.Array,
    n_rep: int,
    scale: float,
    chunk_blocks: int,
    n_chunks: jax.Array,
) -> jax.Array:
    """Lazy paged attention for one decode step of one layer.

    Instead of gathering the whole block table (``[b, T*block_size, ...]``
    per layer even when a lane holds 3 tokens), slide a static-width window
    of ``chunk_blocks`` table columns and fold each chunk into an online
    softmax (flash-decoding style: running max / denominator / weighted
    accumulator, all f32).  ``n_chunks`` — ``ceil((max_pos+1)/chunk)`` — is
    a traced scalar, so the loop lowers to a single ``while`` and the step
    keeps exactly one trace no matter how long the active lanes are.
    Masked positions use the same finite ``NEG_INF`` the full path uses;
    their ``exp`` underflows to zero, so chunked and full attention agree
    to f32 reassociation error.  Returns ``[b, n_heads, 1, head_dim]``.
    """
    b, t = block_tables.shape
    block_size = k_cache_i.shape[1]
    chunk_tokens = chunk_blocks * block_size
    n_heads, head_dim = q.shape[1], q.shape[3]
    kv_heads = k_cache_i.shape[2]

    def body(c, carry):
        m, l, acc = carry
        tbl = jax.lax.dynamic_slice(block_tables, (0, c * chunk_blocks), (b, chunk_blocks))
        keys = k_cache_i[tbl].reshape(b, chunk_tokens, kv_heads, head_dim)
        vals = v_cache_i[tbl].reshape(b, chunk_tokens, kv_heads, head_dim)
        keys = _repeat_kv(keys.transpose(0, 2, 1, 3), n_rep)
        vals = _repeat_kv(vals.transpose(0, 2, 1, 3), n_rep)
        s = (
            jnp.einsum("bhqd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32)
            * scale
        )  # [b, h, 1, chunk_tokens]
        k_idx = c * chunk_tokens + jnp.arange(chunk_tokens)
        msk = (k_idx[None, :] <= pos[:, None]) & active[:, None]  # [b, chunk_tokens]
        s = jnp.where(msk[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vals.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    init = (
        jnp.full((b, n_heads, 1, 1), NEG_INF, jnp.float32),
        jnp.zeros((b, n_heads, 1, 1), jnp.float32),
        jnp.zeros((b, n_heads, 1, head_dim), jnp.float32),
    )
    _, l, acc = jax.lax.fori_loop(0, n_chunks, body, init)
    return acc / jnp.maximum(l, 1e-30)


def transformer_decode(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    cache: Dict[str, jax.Array],
    *,
    chunk_blocks: int = 0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One decode step over the paged cache for every lane at once.

    ``tokens`` [B] the token each lane just consumed; ``positions`` [B] its
    global position (-1 marks an empty lane: it reads/writes the scratch
    block and its logits are garbage the caller ignores); ``block_tables``
    [B, T].  Returns (logits [B, vocab] f32, cache).  Shapes are lane-count
    static, so a mixed stream of request lengths never retraces — the
    continuous batcher joins and retires sequences by editing lane state,
    not by reshaping the batch.

    ``chunk_blocks`` > 0 selects the lazy paged path: gather the table in
    static windows of that many columns and only run
    ``ceil((max_pos+1)/(chunk_blocks*block_size))`` attention passes
    (:func:`_paged_attention_chunked`), instead of materializing the full
    ``[b, T*block_size, kv_heads, head_dim]`` gather every step.  0 keeps
    the original full-table gather.  Both paths share every projection and
    the cache-write scatter, and agree to f32 tolerance.
    """
    _check_decodable(cfg)
    block_size = cache["k"].shape[2]
    b = tokens.shape[0]
    t = block_tables.shape[1]
    kv_len = t * block_size
    dt = cfg.dtype
    active = positions >= 0
    pos = jnp.maximum(positions, 0)
    x = jnp.take(params["embed"]["embedding"].astype(dt), tokens[:, None], axis=0)
    phys = jnp.where(
        active,
        jnp.take_along_axis(block_tables, (pos // block_size)[:, None], axis=1)[:, 0],
        0,
    )
    slot = pos % block_size
    k_pos = jnp.arange(kv_len)
    # attend to every cache position up to and including the current token
    mask = (k_pos[None, :] <= pos[:, None]) & active[:, None]  # [B, kv_len]
    k_cache, v_cache = cache["k"], cache["v"]
    n_rep = cfg.n_heads // cfg.kv_heads
    scale = cfg.head_dim ** -0.5
    n_chunks = None
    if chunk_blocks:
        if t % chunk_blocks:
            raise ValueError(
                f"chunk_blocks={chunk_blocks} must divide the table width {t}"
            )
        chunk_tokens = chunk_blocks * block_size
        n_chunks = jnp.minimum(
            jnp.max(jnp.where(active, pos, 0)) // chunk_tokens + 1, t // chunk_blocks
        )
    for i in range(cfg.n_layers):
        blk = params[f"block_{i}"]
        h = _rms_apply(x, blk["ln1"]["scale"])
        q, k, v = _attn_proj(blk["attn"], h, dt)  # [b, heads|kv, 1, hd]
        q = _rope_batched(q, pos, cfg.rope_theta)
        k = _rope_batched(k, pos, cfg.rope_theta)
        # write this token's k/v, then attend against the updated pool so
        # the step sees its own key (standard causal self-attention)
        k_cache = k_cache.at[i, phys, slot].set(k[:, :, 0, :])
        v_cache = v_cache.at[i, phys, slot].set(v[:, :, 0, :])
        if chunk_blocks:
            att = _paged_attention_chunked(
                q, k_cache[i], v_cache[i], block_tables, pos, active,
                n_rep, scale, chunk_blocks, n_chunks,
            ).astype(dt)
        else:
            keys = k_cache[i][block_tables].reshape(b, kv_len, cfg.kv_heads, -1)
            vals = v_cache[i][block_tables].reshape(b, kv_len, cfg.kv_heads, -1)
            keys = _repeat_kv(keys.transpose(0, 2, 1, 3), n_rep)
            vals = _repeat_kv(vals.transpose(0, 2, 1, 3), n_rep)
            logits = (
                jnp.einsum(
                    "bhqd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32
                )
                * scale
            )
            logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1)
            att = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vals.dtype), vals)
        att = att.transpose(0, 2, 1, 3)  # [b, 1, h, hd]
        x = x + jnp.einsum(
            "bshk,hkD->bsD", att, blk["attn"]["wo"]["kernel"].astype(dt)
        )
        x = x + _mlp_apply(blk["mlp"], _rms_apply(x, blk["ln2"]["scale"]), dt)
    x = _rms_apply(x, params["ln_f"]["scale"])
    logits = (x[:, 0, :] @ params["lm_head"]["kernel"].astype(dt)).astype(jnp.float32)
    return logits, {"k": k_cache, "v": v_cache}


def transformer_prefill_suffix(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jax.Array,
    start_lens: jax.Array,
    prompt_lens: jax.Array,
    block_tables: jax.Array,
    cache: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Prefill only the un-cached suffix of each prompt (prefix caching).

    ``tokens`` [B, S] is the FULL prompt padded to a multiple of the block
    size; ``start_lens`` [B] how many leading tokens already sit in cache
    blocks mapped into ``block_tables`` (block-aligned by construction —
    only full blocks are shared); ``prompt_lens`` [B] the real lengths.
    Returns (last_logits [B, vocab] f32 — the logits at ``prompt_len - 1``
    each lane samples its first token from — and the updated cache).

    The walk is one block of tokens per iteration of a dynamic-trip-count
    ``fori_loop`` (``start//block_size .. ceil(len/block_size)``), so the
    compute and the single compiled trace scale with the SUFFIX, not the
    padded prompt width: a 70%-shared system prompt pays for its unique
    tail only.  Queries attend against keys READ FROM THE CACHE (prefix
    blocks written by whoever prefilled them first, suffix blocks written
    by this call just before attending), masked ``k_pos <= q_pos``, which
    makes a warm start and a cold ``start=0`` run of the same prompt
    bitwise identical — the parity the prefix-cache admission tests pin.
    Positions outside ``[start, len)`` write to scratch block 0 and their
    logits are never selected; since keys come from the cache rather than
    the local projection, garbage padding columns cannot leak into valid
    ones.
    """
    _check_decodable(cfg)
    block_size = cache["k"].shape[2]
    b, s = tokens.shape
    if s % block_size:
        raise ValueError(
            f"suffix prefill needs tokens padded to the block size "
            f"(got S={s}, block_size={block_size})"
        )
    t = block_tables.shape[1]
    kv_len = t * block_size
    dt = cfg.dtype
    n_rep = cfg.n_heads // cfg.kv_heads
    scale = cfg.head_dim ** -0.5
    c_lo = jnp.min(start_lens) // block_size
    c_hi = (jnp.max(prompt_lens) + block_size - 1) // block_size
    k_pos = jnp.arange(kv_len)

    def body(c, carry):
        k_cache, v_cache, last_logits = carry
        toks = jax.lax.dynamic_slice(tokens, (0, c * block_size), (b, block_size))
        p = c * block_size + jnp.arange(block_size)  # absolute positions [bs]
        valid = (p[None, :] >= start_lens[:, None]) & (
            p[None, :] < prompt_lens[:, None]
        )  # [b, bs]
        tbl_col = jax.lax.dynamic_slice(block_tables, (0, c), (b, 1))  # [b, 1]
        phys = jnp.where(valid, tbl_col, 0)
        slots = jnp.broadcast_to(jnp.arange(block_size)[None, :], (b, block_size))
        att_mask = k_pos[None, :] <= p[:, None]  # [bs, kv_len]
        x = jnp.take(params["embed"]["embedding"].astype(dt), toks, axis=0)
        for i in range(cfg.n_layers):
            blk = params[f"block_{i}"]
            h = _rms_apply(x, blk["ln1"]["scale"])
            q, k, v = _attn_proj(blk["attn"], h, dt)  # [b, heads|kv, bs, hd]
            q = _rope(q, p, cfg.rope_theta)
            k = _rope(k, p, cfg.rope_theta)
            # write this block's k/v first, then attend through the cache:
            # the block's own causal keys and the cached prefix are read
            # from the same pool, so warm and cold prefills see identical
            # stored bits
            k_cache = k_cache.at[i, phys, slots].set(k.transpose(0, 2, 1, 3))
            v_cache = v_cache.at[i, phys, slots].set(v.transpose(0, 2, 1, 3))
            keys = k_cache[i][block_tables].reshape(b, kv_len, cfg.kv_heads, -1)
            vals = v_cache[i][block_tables].reshape(b, kv_len, cfg.kv_heads, -1)
            keys = _repeat_kv(keys.transpose(0, 2, 1, 3), n_rep)
            vals = _repeat_kv(vals.transpose(0, 2, 1, 3), n_rep)
            logits = (
                jnp.einsum(
                    "bhqd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32
                )
                * scale
            )
            logits = jnp.where(att_mask[None, None, :, :], logits, NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1)
            att = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vals.dtype), vals)
            att = att.transpose(0, 2, 1, 3)  # [b, bs, h, hd]
            x = x + jnp.einsum(
                "bshk,hkD->bsD", att, blk["attn"]["wo"]["kernel"].astype(dt)
            )
            x = x + _mlp_apply(blk["mlp"], _rms_apply(x, blk["ln2"]["scale"]), dt)
        x = _rms_apply(x, params["ln_f"]["scale"])
        logits = (x @ params["lm_head"]["kernel"].astype(dt)).astype(jnp.float32)
        sel = prompt_lens - 1 - c * block_size  # [b]
        contains = (sel >= 0) & (sel < block_size)
        idx = jnp.clip(sel, 0, block_size - 1)
        row = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0, :]
        last_logits = jnp.where(contains[:, None], row, last_logits)
        return k_cache, v_cache, last_logits

    init = (
        cache["k"],
        cache["v"],
        jnp.zeros((b, cfg.vocab_size), jnp.float32),
    )
    k_cache, v_cache, last_logits = jax.lax.fori_loop(c_lo, c_hi, body, init)
    return last_logits, {"k": k_cache, "v": v_cache}


class LMTrial(JaxTrial):
    """Language-model trial over synthetic (or user-supplied) token data.

    Hyperparameters: lr, global_batch_size, seq_len, vocab_size, d_model,
    n_layers, n_heads, n_kv_heads, d_ff, attention (auto/flash/ring/
    reference), remat, warmup_steps, dataset_size, pipe_microbatches.

    When the context mesh has a ``pipe`` axis of size P > 1, the trial
    restructures its params into stacked pipeline stages and trains through
    the GPipe schedule (``pipeline_forward``) — same init, same loss as
    pipe=1 (verified by ``tests/test_pipeline_e2e.py``).
    """

    def _pipe_stages(self) -> int:
        mesh = self.context.mesh
        return int(mesh.shape.get(MeshAxes.PIPELINE, 1)) if mesh is not None else 1

    def _pipe_microbatches(self, batch: int) -> int:
        m = self.context.get_hparam("pipe_microbatches", None)
        if m:
            return int(m)
        # default: 2 microbatches per stage (bubble fraction (P-1)/(M+P-1)),
        # shrunk to the largest divisor of the batch
        m = min(batch, 2 * self._pipe_stages())
        while batch % m:
            m -= 1
        return m

    def _pipe_schedule(self) -> Tuple[str, int]:
        """(schedule, virtual_stages) resolution: trial hparam override
        wins, else the experiment's ``optimizations`` knobs, else gpipe —
        the same precedence as ``_quant_mode``."""
        g = self.context.get_hparam
        opt = (
            self.context.exp_config.optimizations
            if self.context.exp_config is not None
            else None
        )
        name = g("pipeline_schedule", None)
        if name is None:
            name = opt.pipeline_schedule if opt is not None else "gpipe"
        v = g("virtual_stages", None)
        if v is None:
            v = opt.virtual_stages if opt is not None else 1
        return str(name), int(v)

    def pipeline_schedule_spec(self):
        """The trial's ``PipelineSchedule`` (None without a pipe axis) —
        the Trainer reads this for the jit-cache key and the goodput
        ledger's ``step.bubble`` analytic tick model."""
        pipe = self._pipe_stages()
        if pipe <= 1:
            return None
        from determined_tpu.parallel.pipeline import PipelineSchedule

        name, v = self._pipe_schedule()
        return PipelineSchedule(
            name=name,
            n_stages=pipe,
            num_microbatches=self._pipe_microbatches(
                self.context.get_global_batch_size()
            ),
            virtual_stages=v,
        )

    def _quant_mode(self) -> str:
        """quantized_matmul resolution: trial hparam override wins, else
        the experiment's ``optimizations.quantized_matmul`` knob, else
        off.  Platform-gated here (setup time) so fp8 on an unsupported
        chip fails with a clear InvalidExperimentConfig, not a lowering
        error mid-compile."""
        from determined_tpu.train._quant import require_platform

        mode = self.context.get_hparam("quantized_matmul", None)
        if mode is None and self.context.exp_config is not None:
            mode = self.context.exp_config.optimizations.quantized_matmul
        mode = str(mode) if mode else "none"
        require_platform(mode)
        return mode

    def _cfg(self) -> TransformerConfig:
        g = self.context.get_hparam
        pipe = self._pipe_stages()
        if pipe > 1 and int(g("moe_experts", 0)) > 0:
            # MoE composes with pipe when every chunk sees the same layer
            # pattern: the MoE period must divide layers-per-chunk
            _, vstages = self._pipe_schedule()
            lps = int(g("n_layers", 2)) // (pipe * vstages)
            if lps == 0 or lps % int(g("moe_every", 2)):
                raise ValueError(
                    f"pipe={pipe} with MoE needs moe_every ({g('moe_every', 2)}) "
                    f"to divide layers-per-chunk ({lps})"
                )
        return TransformerConfig(
            vocab_size=int(g("vocab_size", 2048)),
            d_model=int(g("d_model", 256)),
            n_layers=int(g("n_layers", 2)),
            n_heads=int(g("n_heads", 8)),
            n_kv_heads=g("n_kv_heads", None),
            d_ff=g("d_ff", None),
            max_seq_len=int(g("seq_len", 512)),
            attention_impl=str(g("attention", "auto")),
            remat=bool(g("remat", False)),
            dtype=jnp.bfloat16 if bool(g("bf16", True)) else jnp.float32,
            moe_experts=int(g("moe_experts", 0)),
            moe_every=int(g("moe_every", 2)),
            moe_capacity_factor=float(g("moe_capacity_factor", 1.25)),
            moe_aux_weight=float(g("moe_aux_weight", 0.01)),
            quantized_matmul=self._quant_mode(),
        )

    @property
    def tokens_per_sample(self) -> int:
        """Tokens one sample contributes per step — the goodput ledger's
        tokens/s denominator (observability/_goodput.py)."""
        return int(self.context.get_hparam("seq_len", 512))

    @property
    def flops_per_token(self) -> float:
        """Fwd+bwd matmul FLOPs per token by the standard 6N + attention
        convention (same accounting as bench.py), for the ledger's MFU
        estimate."""
        cfg = self._cfg()
        d, L, V = cfg.d_model, cfg.n_layers, cfg.vocab_size
        n_params = L * (4 * d * d + 12 * d * d) + V * d
        return float(6 * n_params + 12 * L * cfg.max_seq_len * d)

    def build_model(self) -> TransformerLM:
        return TransformerLM(self._cfg(), mesh=self.context.mesh)

    def build_optimizer(self) -> optax.GradientTransformation:
        g = self.context.get_hparam
        lr = float(g("lr", 3e-4))
        warmup = int(g("warmup_steps", 100))
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, warmup, int(g("decay_steps", 10000))
        )
        self.lr_schedule = schedule  # surfaced as the per-batch `lr` metric
        # adam first-moment dtype: bf16 halves its HBM traffic (the
        # optimizer update is bandwidth-bound); second moment stays f32
        # for the rsqrt's dynamic range
        mu_dtype = jnp.bfloat16 if bool(g("adam_mu_bf16", False)) else None
        fused = g("fused_adamw", "auto")
        if fused == "auto":
            fused = jax.default_backend() == "tpu"
        if fused:
            # single-sweep Pallas AdamW+clip (ops/fused_adamw.py): 8 HBM
            # passes vs optax's measured 9 on the bandwidth-bound update
            from determined_tpu.ops.fused_adamw import fused_adamw

            return fused_adamw(
                schedule,
                weight_decay=float(g("weight_decay", 0.01)),
                clip_norm=float(g("grad_clip", 1.0)),
                mu_dtype=mu_dtype,
            )
        return optax.chain(
            optax.clip_by_global_norm(float(g("grad_clip", 1.0))),
            optax.adamw(
                schedule,
                weight_decay=float(g("weight_decay", 0.01)),
                mu_dtype=mu_dtype,
            ),
        )

    def _dataset(self, seed: int) -> SyntheticDataset:
        g = self.context.get_hparam
        seq = int(g("seq_len", 512))
        size = int(g("dataset_size", 2048))
        return SyntheticDataset(
            {"tokens": ((seq + 1,), np.int32, int(g("vocab_size", 2048)))},
            size=size,
            seed=seed,
        )

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(0),
            self.context.get_global_batch_size(),
            shuffle=True,
            seed=self.context.seed,
        )

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(1),
            self.context.get_global_batch_size(),
            shuffle=False,
            seed=self.context.seed,
        )

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        return (jnp.asarray(batch["tokens"])[:, :-1],)

    def restructure_params(self, params: Any) -> Any:
        # pipe > 1: restack per-layer blocks into pipeline stages.  Kept
        # OUT of init_params so the trainer can stage it on jax versions
        # where a jitted stack into pipe-sharded out_shardings SUMS the
        # replicated operands (parallel/_compat.py sharded_restack_safe):
        # pipe>1 trials used to start from doubled block weights — the
        # whole ~1.5% pipe-parity drift ROADMAP tracked.
        pipe = self._pipe_stages()
        if pipe > 1:
            _, vstages = self._pipe_schedule()
            return split_pipeline_params(params, pipe, vstages)
        return params

    def param_logical_specs(self, params: Any) -> Any:
        if self._pipe_stages() <= 1:
            return None
        from flax.core import meta as flax_meta

        from determined_tpu.train._trainer import _specs_from_flax_metadata

        outer = _specs_from_flax_metadata(params["outer"])
        if outer is None:
            outer = jax.tree.map(lambda _: None, flax_meta.unbox(params["outer"]))
        from determined_tpu.parallel.pipeline import _path_has_expert_leaf

        _, vstages = self._pipe_schedule()
        # interleaved leaves lead [stage, virtual, ...]; the virtual-stage
        # dim stays unsharded (each rank owns all V of its chunks)
        head = ("stage", None) if vstages > 1 else ("stage",)

        def block_spec(path, a):
            if _path_has_expert_leaf(path):
                return head + ("expert",) + (None,) * (a.ndim - len(head) - 1)
            return head + (None,) * (a.ndim - len(head))

        blocks = jax.tree_util.tree_map_with_path(block_spec, params["blocks"])
        return {"outer": outer, "blocks": blocks}

    def loss(
        self, model: TransformerLM, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        g = self.context.get_hparam
        fused = g("fused_ce", "auto")
        if fused == "auto":
            fused = model.cfg.vocab_size >= 8192
        if self._pipe_stages() > 1:
            return self._pipeline_loss(model, params, inputs, targets, fused)
        if fused:
            from flax.core import meta as flax_meta

            from determined_tpu.ops.cross_entropy import fused_cross_entropy

            hidden, moe_aux = model.apply(
                params, inputs, return_hidden=True, return_aux=True
            )
            kernel = flax_meta.unbox(params["params"]["lm_head"]["kernel"])
            chunk = g("ce_chunk", None)
            shards = self.context.batch_axis_size if self.context.mesh is not None else 1
            loss = fused_cross_entropy(
                hidden,
                kernel,
                targets,
                chunk_size=None if chunk in (None, "auto") else int(chunk),
                compute_dtype=model.cfg.dtype,
                batch_shards=shards,
                bf16_residual=bool(g("ce_bf16_residual", False)),
            )
        else:
            logits, moe_aux = model.apply(params, inputs, return_aux=True)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
        metrics = {"perplexity": jnp.exp(loss)}
        if model.cfg.moe_experts > 0:
            metrics["moe_aux_loss"] = moe_aux
            loss = loss + model.cfg.moe_aux_weight * moe_aux
        return loss, metrics

    def _pipeline_loss(
        self,
        model: TransformerLM,
        params: Any,
        inputs: jax.Array,
        targets: jax.Array,
        fused: bool,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Loss through the configured microbatch schedule (mesh has a
        pipe axis > 1)."""
        g = self.context.get_hparam
        mb = self._pipe_microbatches(inputs.shape[0])
        sched, vstages = self._pipe_schedule()
        if fused:
            from flax.core import meta as flax_meta

            from determined_tpu.ops.cross_entropy import fused_cross_entropy

            hidden, moe_aux = pipeline_forward(
                model.cfg, self.context.mesh, params, inputs, mb,
                return_hidden=True, rules=self.context.rules, return_aux=True,
                schedule=sched, virtual_stages=vstages,
            )
            kernel = flax_meta.unbox(params["outer"]["params"]["lm_head"]["kernel"])
            chunk = g("ce_chunk", None)
            shards = self.context.batch_axis_size
            loss = fused_cross_entropy(
                hidden,
                kernel,
                targets,
                chunk_size=None if chunk in (None, "auto") else int(chunk),
                compute_dtype=model.cfg.dtype,
                batch_shards=shards,
                bf16_residual=bool(g("ce_bf16_residual", False)),
            )
        else:
            logits, moe_aux = pipeline_forward(
                model.cfg, self.context.mesh, params, inputs, mb,
                rules=self.context.rules, return_aux=True,
                schedule=sched, virtual_stages=vstages,
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
        metrics = {"perplexity": jnp.exp(loss)}
        if model.cfg.moe_experts > 0:
            metrics["moe_aux_loss"] = moe_aux
            loss = loss + model.cfg.moe_aux_weight * moe_aux
        return loss, metrics

    def evaluate_batch(
        self, model: TransformerLM, params: Any, batch: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        loss, metrics = self.loss(model, params, batch, jax.random.key(0))
        return {"validation_loss": loss, "validation_perplexity": metrics["perplexity"]}
