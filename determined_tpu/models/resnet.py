"""ResNet for 32x32 image classification — the cifar10_pytorch workload
(BASELINE.json names it; the reference snapshot lacks the example, so this
is authored from the mnist/iris patterns per SURVEY §2.11).

TPU-first notes: convs lower onto the MXU as implicit GEMMs, so channels
stay multiples of 8 and compute runs in bf16 with f32 params.
Normalization is **GroupNorm, not BatchNorm** — deliberately: BatchNorm's
running statistics are mutable cross-batch state that (a) breaks the pure
`loss(params, batch)` step this framework jits and donates, and (b) needs
cross-replica stat sync under data parallelism (the reference wraps torch
SyncBN for exactly this reason).  GroupNorm is stateless, batch-size
independent, and equally accurate at this scale.  Conv kernels replicate
over the mesh (small next to activations; FSDP over them is not worth the
collectives at this size) — data parallelism comes from the batch axis.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from determined_tpu.data import DataLoader, InMemoryDataset
from determined_tpu.train._trial import JaxTrial


class ResidualBlock(nn.Module):
    channels: int
    stride: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        conv = lambda ch, st, name: nn.Conv(  # noqa: E731
            ch, (3, 3), strides=(st, st), padding="SAME", use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32, name=name,
        )
        norm = lambda name: nn.GroupNorm(  # noqa: E731
            num_groups=8, dtype=self.dtype, param_dtype=jnp.float32, name=name,
        )
        residual = x
        y = nn.relu(norm("gn1")(conv(self.channels, self.stride, "conv1")(x)))
        y = norm("gn2")(conv(self.channels, 1, "conv2")(y))
        if residual.shape != y.shape:
            residual = norm("gn_proj")(
                conv(self.channels, self.stride, "proj")(x)
            )
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet-(6n+2) family: stages of widths x depths over 32x32 inputs."""

    num_classes: int = 10
    widths: Sequence[int] = (16, 32, 64)
    depth_per_stage: int = 3
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.dtype)
        x = nn.Conv(self.widths[0], (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32, name="stem")(x)
        x = nn.relu(nn.GroupNorm(num_groups=8, dtype=self.dtype,
                                 param_dtype=jnp.float32, name="gn_stem")(x))
        for stage, width in enumerate(self.widths):
            for block in range(self.depth_per_stage):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = ResidualBlock(width, stride, self.dtype,
                                  name=f"s{stage}b{block}")(x)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, param_dtype=jnp.float32,
                        dtype=jnp.float32, name="head")(x)


def cifar_like(size: int = 4096, num_classes: int = 10, seed: int = 0) -> InMemoryDataset:
    """Class-separable synthetic 32x32x3 dataset (loads nothing: zero
    egress on TPU pods), so accuracy provably improves in tests."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size).astype(np.int32)
    # each class gets a distinct low-frequency template + noise
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    templates = np.stack(
        [
            np.stack(
                [
                    np.sin((c + 1) * np.pi * xx),
                    np.cos((c + 2) * np.pi * yy),
                    np.sin((c + 1) * np.pi * (xx + yy)),
                ],
                axis=-1,
            )
            for c in range(num_classes)
        ]
    )
    images = templates[labels] + rng.normal(0, 0.4, (size, 32, 32, 3)).astype(np.float32)
    return InMemoryDataset({"image": images.astype(np.float32), "label": labels})


class CifarTrial(JaxTrial):
    """hparams: lr, momentum, global_batch_size, dataset_size,
    depth_per_stage, widths, num_classes, bf16."""

    def build_model(self) -> ResNet:
        g = self.context.get_hparam
        return ResNet(
            num_classes=int(g("num_classes", 10)),
            widths=tuple(g("widths", (16, 32, 64))),
            depth_per_stage=int(g("depth_per_stage", 3)),
            dtype=jnp.bfloat16 if bool(g("bf16", True)) else jnp.float32,
        )

    def build_optimizer(self) -> optax.GradientTransformation:
        g = self.context.get_hparam
        return optax.sgd(float(g("lr", 0.1)), momentum=float(g("momentum", 0.9)))

    def _dataset(self, train: bool) -> InMemoryDataset:
        g = self.context.get_hparam
        return cifar_like(
            size=int(g("dataset_size", 4096)),
            num_classes=int(g("num_classes", 10)),
            seed=0 if train else 1,
        )

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(True), self.context.get_global_batch_size(),
                          shuffle=True, seed=self.context.seed)

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(False), self.context.get_global_batch_size(),
                          shuffle=False, seed=self.context.seed)

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        return (jnp.asarray(batch["image"]),)

    def loss(self, model: ResNet, params: Any, batch: Dict[str, jax.Array], rng):
        logits = model.apply(params, batch["image"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = (logits.argmax(-1) == batch["label"]).mean()
        return loss, {"accuracy": acc}

    def evaluate_batch(self, model: ResNet, params: Any, batch: Dict[str, jax.Array]):
        loss, metrics = self.loss(model, params, batch, jax.random.key(0))
        return {"validation_loss": loss, "validation_accuracy": metrics["accuracy"]}
