"""HuggingFace Flax BERT sequence-classification trial.

Reference: ``examples/hf_trainer_api`` (HF Trainer + Core API callbacks) —
the reference wraps torch Trainer; here the HF **Flax** module drops
straight into the JaxTrial contract, so the platform's jitted/donated step,
mesh parallelism, checkpointing and preemption all apply to an off-the-shelf
transformers model with ~80 lines of glue.

Offline by design: the model initializes from a ``BertConfig`` (random
weights) and trains on a synthetic separable token task — TPU pods have no
egress.  To fine-tune real weights, point ``hparams.pretrained_dir`` at a
local ``save_pretrained`` directory.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_tpu.data import DataLoader, InMemoryDataset
from determined_tpu.models._hf_common import HFModuleHolder
from determined_tpu.train._trial import JaxTrial


def synthetic_classification(
    size: int, seq_len: int, vocab: int, num_labels: int, seed: int
) -> InMemoryDataset:
    """Label = which label-specific marker token dominates the sequence."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_labels, size).astype(np.int32)
    ids = rng.integers(num_labels + 1, vocab, (size, seq_len)).astype(np.int32)
    # plant marker tokens (token id == label + 1) in ~25% of positions
    mask = rng.random((size, seq_len)) < 0.25
    ids[mask] = (labels[:, None] + 1).repeat(seq_len, 1)[mask]
    return InMemoryDataset({"input_ids": ids, "label": labels})


class _BertModule(HFModuleHolder):
    """Holder wiring BERT's forward signature into the shared HF plumbing
    (``_hf_common.HFModuleHolder`` owns the pretrained_dir contract)."""

    @classmethod
    def _model_cls(cls):
        from transformers import FlaxBertForSequenceClassification

        return FlaxBertForSequenceClassification

    def _forward_args(self, input_ids):
        return (
            input_ids,
            jnp.ones_like(input_ids),
            jnp.zeros_like(input_ids),
            None,
            None,
        )


class BertClassifyTrial(JaxTrial):
    """hparams: lr, global_batch_size, seq_len, vocab_size, hidden_size,
    num_layers, num_heads, num_labels, dataset_size, warmup_steps."""

    def _hp(self, name, default):
        return self.context.get_hparam(name, default)

    def build_model(self) -> _BertModule:
        from transformers import BertConfig

        cfg = BertConfig(
            vocab_size=int(self._hp("vocab_size", 1024)),
            hidden_size=int(self._hp("hidden_size", 128)),
            num_hidden_layers=int(self._hp("num_layers", 2)),
            num_attention_heads=int(self._hp("num_heads", 4)),
            intermediate_size=4 * int(self._hp("hidden_size", 128)),
            max_position_embeddings=max(int(self._hp("seq_len", 64)), 64),
            num_labels=int(self._hp("num_labels", 4)),
        )
        return _BertModule(
            cfg, seed=self.context.seed,
            pretrained_dir=str(self._hp("pretrained_dir", "")),
        )

    def build_optimizer(self) -> optax.GradientTransformation:
        lr = float(self._hp("lr", 5e-4))
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, int(self._hp("warmup_steps", 20)), int(self._hp("decay_steps", 2000))
        )
        if self._hp("fused_adamw", False):
            # opt-in only: the A/B on the chip (BASELINE.md r5) measured
            # the optax chain ~0.7% FASTER for this workload — the HF
            # param tree's optimizer share is too small to repay the
            # fused kernel's launch overhead.  Kept as a knob because the
            # semantics match (no clip) and bigger fine-tunes may differ.
            from determined_tpu.ops.fused_adamw import fused_adamw

            return fused_adamw(schedule, weight_decay=0.01, clip_norm=None)
        return optax.adamw(schedule, weight_decay=0.01)

    def _dataset(self, train: bool) -> InMemoryDataset:
        return synthetic_classification(
            size=int(self._hp("dataset_size", 1024)),
            seq_len=int(self._hp("seq_len", 64)),
            vocab=int(self._hp("vocab_size", 1024)),
            num_labels=int(self._hp("num_labels", 4)),
            seed=0 if train else 1,
        )

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(True), self.context.get_global_batch_size(),
                          shuffle=True, seed=self.context.seed)

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(False), self.context.get_global_batch_size(),
                          shuffle=False, seed=self.context.seed)

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        return (jnp.asarray(batch["input_ids"]),)

    def init_params(self, model: _BertModule, rng: jax.Array, sample_batch):
        return model.init(rng, jnp.asarray(sample_batch["input_ids"]))

    def loss(self, model: _BertModule, params: Any, batch: Dict[str, jax.Array], rng):
        out = model.apply(
            params, batch["input_ids"], deterministic=False, rngs={"dropout": rng}
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            out.logits, batch["label"]
        ).mean()
        acc = (out.logits.argmax(-1) == batch["label"]).mean()
        return loss, {"accuracy": acc}

    def evaluate_batch(self, model: _BertModule, params: Any, batch):
        out = model.apply(params, batch["input_ids"], deterministic=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            out.logits, batch["label"]
        ).mean()
        acc = (out.logits.argmax(-1) == batch["label"]).mean()
        return {"validation_loss": loss, "validation_accuracy": acc}
