"""MNIST models + trial: the minimum end-to-end slice.

Reference: ``examples/tutorials/mnist_pytorch/model_def.py`` (conv net under
PyTorchTrial).  Here: flax modules with logical-axis partitioning metadata
so the SAME model runs DP, FSDP, or TP by changing only the MeshConfig.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from determined_tpu.data import DataLoader, mnist_like
from determined_tpu.train._trial import JaxTrial


class MnistMLP(nn.Module):
    """Two-layer MLP; hidden dim carries the "mlp" logical axis so a tensor
    mesh axis shards it (Megatron-style column/row split, XLA-inserted
    collectives)."""

    hidden: int = 128
    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(
            self.hidden,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("embed", "mlp")
            ),
            name="fc1",
        )(x)
        x = nn.relu(x)
        x = nn.Dense(
            self.num_classes,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("mlp", None)
            ),
            name="fc2",
        )(x)
        return x


class MnistCNN(nn.Module):
    """Conv net matching the reference tutorial's shape (2 conv + 2 dense)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(32, (3, 3), name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(
            128,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), (None, "mlp")
            ),
            name="fc1",
        )(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, name="fc2")(x)
        return x


class MnistTrial(JaxTrial):
    """The flagship "tutorial" trial — hyperparameters mirror the reference
    mnist example (lr, hidden size, global_batch_size)."""

    def build_model(self) -> nn.Module:
        kind = self.context.get_hparam("model", "mlp")
        if kind == "cnn":
            return MnistCNN()
        return MnistMLP(hidden=int(self.context.get_hparam("hidden", 128)))

    def build_optimizer(self) -> optax.GradientTransformation:
        lr = float(self.context.get_hparam("lr", 1e-3))
        # inject_hyperparams moves lr into opt_state (read by the traced
        # step at run time) instead of baking it into the HLO: searches
        # that vary ONLY lr — random/ASHA draws, PBT perturbations —
        # share one compiled step through train/_jit_cache.py
        return optax.inject_hyperparams(optax.adam)(learning_rate=lr)

    def compile_cache_runtime_hparams(self) -> Tuple[str, ...]:
        return ("lr",)

    def _dataset(self, train: bool):
        size = int(self.context.get_hparam("dataset_size", 4096))
        return mnist_like(size=size, seed=0 if train else 1)

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(train=True),
            self.context.get_global_batch_size(),
            shuffle=True,
            seed=self.context.seed,
        )

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(train=False),
            self.context.get_global_batch_size(),
            shuffle=False,
            seed=self.context.seed,
        )

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        return (batch["image"],)

    def loss(
        self, model: nn.Module, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = model.apply(params, batch["image"])
        labels = batch["label"]
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, {"accuracy": acc}

    def evaluate_batch(
        self, model: nn.Module, params: Any, batch: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        loss, metrics = self.loss(model, params, batch, jax.random.key(0))
        return {"validation_loss": loss, "validation_accuracy": metrics["accuracy"]}
