"""Denoising diffusion (DDPM) model family: UNet + noise-prediction trial.

Reference parity: the reference ships a diffusion example family
(``examples/diffusion/``, a HF-diffusers textual-inversion fine-tune under
Core API).  TPU-first redesign rather than a wrapper: a self-contained
flax UNet whose convs/denses carry logical partitioning axes (the same
mesh machinery as every other model family), a cosine noise schedule, a
jittable training loss (random-timestep epsilon prediction), and an
ancestral sampler expressed as ``lax.scan`` so the entire reverse process
is one compiled loop — no Python stepping, no host syncs (SURVEY §7:
compiler-friendly control flow).

Convs run on the MXU as implicit GEMMs; channel widths carry the "mlp"
logical axis so a tensor mesh axis shards them.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from determined_tpu.data import DataLoader, mnist_like
from determined_tpu.train._trial import JaxTrial


def _groups(channels: int, want: int = 8) -> int:
    """Largest group count <= want that divides the channel width — any
    base_channels value is valid (GroupNorm requires divisibility)."""
    g = min(want, channels)
    while channels % g:
        g -= 1
    return g


def timestep_embedding(t: jax.Array, dim: int, max_period: int = 10000) -> jax.Array:
    """Sinusoidal timestep embedding [batch, dim] (f32 for stable freqs)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResBlock(nn.Module):
    """Conv residual block with time-embedding FiLM conditioning."""

    channels: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, temb: jax.Array) -> jax.Array:
        h = nn.GroupNorm(num_groups=_groups(x.shape[-1]), dtype=self.dtype)(x)
        h = nn.silu(h)
        h = nn.Conv(
            self.channels, (3, 3), dtype=self.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), (None, None, None, "mlp")
            ),
            name="conv1",
        )(h)
        # FiLM: scale/shift from the time embedding
        ss = nn.Dense(2 * self.channels, dtype=self.dtype, name="temb_proj")(
            nn.silu(temb)
        )
        scale, shift = jnp.split(ss[:, None, None, :], 2, axis=-1)
        h = nn.GroupNorm(num_groups=_groups(self.channels), dtype=self.dtype)(h)
        h = h * (1 + scale) + shift
        h = nn.silu(h)
        h = nn.Conv(
            self.channels, (3, 3), dtype=self.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.zeros_init(), (None, None, None, "mlp")
            ),
            name="conv2",
        )(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class SelfAttention2D(nn.Module):
    """Full self-attention over the (small) lowest-resolution feature map."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        y = nn.GroupNorm(num_groups=_groups(c), dtype=self.dtype)(x)
        y = y.reshape(b, h * w, c)
        qkv = nn.Dense(3 * c, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        scale = c ** -0.5
        attn = jax.nn.softmax(
            jnp.einsum("bqc,bkc->bqk", q, k) * scale, axis=-1
        )
        y = jnp.einsum("bqk,bkc->bqc", attn, v)
        y = nn.Dense(c, dtype=self.dtype, kernel_init=nn.initializers.zeros_init(),
                     name="proj")(y)
        return x + y.reshape(b, h, w, c)


class UNet(nn.Module):
    """Small DDPM UNet: down/up path with skip connections, attention at
    the bottleneck.  Sized by ``base_channels`` (default fits tests; real
    runs scale it up — convs are MXU-bound so width is the lever)."""

    base_channels: int = 32
    channel_mults: Tuple[int, ...] = (1, 2)
    out_channels: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, t: jax.Array) -> jax.Array:
        ch = self.base_channels
        temb = timestep_embedding(t, ch * 4).astype(self.dtype)
        temb = nn.Dense(ch * 4, dtype=self.dtype, name="temb1")(temb)
        temb = nn.Dense(ch * 4, dtype=self.dtype, name="temb2")(nn.silu(temb))

        h = nn.Conv(ch, (3, 3), dtype=self.dtype, name="stem")(x.astype(self.dtype))
        # down path: skip saved per level BEFORE pooling, so each up level
        # concatenates a same-resolution tensor
        skips = []
        for i, mult in enumerate(self.channel_mults):
            h = ResBlock(ch * mult, self.dtype, name=f"down{i}")(h, temb)
            skips.append(h)
            if i < len(self.channel_mults) - 1:
                h = nn.avg_pool(h, (2, 2), strides=(2, 2))
        # bottleneck with attention
        mid = ch * self.channel_mults[-1]
        h = ResBlock(mid, self.dtype, name="mid1")(h, temb)
        h = SelfAttention2D(self.dtype, name="mid_attn")(h)
        h = ResBlock(mid, self.dtype, name="mid2")(h, temb)
        # up path
        for i, mult in reversed(list(enumerate(self.channel_mults))):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = ResBlock(ch * mult, self.dtype, name=f"up{i}")(h, temb)
            if i > 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
        h = nn.GroupNorm(num_groups=_groups(h.shape[-1]), dtype=self.dtype)(h)
        h = nn.silu(h)
        return nn.Conv(
            self.out_channels, (3, 3), dtype=self.dtype,
            kernel_init=nn.initializers.zeros_init(), name="head",
        )(h).astype(jnp.float32)


def cosine_schedule(timesteps: int, s: float = 0.008) -> Dict[str, jax.Array]:
    """DDPM cosine betas -> the alpha-bar tables the loss/sampler need."""
    steps = jnp.arange(timesteps + 1, dtype=jnp.float32)
    f = jnp.cos(((steps / timesteps) + s) / (1 + s) * math.pi / 2) ** 2
    alpha_bar = f / f[0]
    betas = jnp.clip(1 - alpha_bar[1:] / alpha_bar[:-1], 0, 0.999)
    alphas = 1 - betas
    alpha_bar = jnp.cumprod(alphas)
    return {
        "betas": betas,
        "alphas": alphas,
        "alpha_bar": alpha_bar,
        "sqrt_ab": jnp.sqrt(alpha_bar),
        "sqrt_1mab": jnp.sqrt(1 - alpha_bar),
    }


def ddpm_sample(
    model: nn.Module,
    params: Any,
    rng: jax.Array,
    shape: Tuple[int, ...],
    timesteps: int = 1000,
) -> jax.Array:
    """Ancestral sampling as ONE ``lax.scan`` over t = T-1..0 — the whole
    reverse chain compiles to a single device loop."""
    sched = cosine_schedule(timesteps)

    def step(x, t):
        eps = model.apply(params, x, jnp.full((shape[0],), t))
        beta = sched["betas"][t]
        alpha = sched["alphas"][t]
        ab = sched["alpha_bar"][t]
        mean = (x - beta / jnp.sqrt(1 - ab) * eps) / jnp.sqrt(alpha)
        noise = jax.random.normal(jax.random.fold_in(rng, t), shape)
        x = mean + jnp.where(t > 0, jnp.sqrt(beta), 0.0) * noise
        return x, None

    x0 = jax.random.normal(rng, shape)
    x, _ = jax.lax.scan(step, x0, jnp.arange(timesteps - 1, -1, -1))
    return x


class DiffusionTrial(JaxTrial):
    """Epsilon-prediction DDPM training (Ho et al. simple loss).

    Hyperparameters: lr, base_channels, timesteps, global_batch_size,
    dataset_size, bf16.
    """

    def _hp(self, name, default):
        return self.context.get_hparam(name, default)

    def build_model(self) -> UNet:
        return UNet(
            base_channels=int(self._hp("base_channels", 32)),
            dtype=jnp.bfloat16 if bool(self._hp("bf16", False)) else jnp.float32,
        )

    def build_optimizer(self) -> optax.GradientTransformation:
        return optax.adamw(float(self._hp("lr", 2e-4)))

    def _dataset(self, train: bool):
        return mnist_like(
            size=int(self._hp("dataset_size", 4096)), seed=0 if train else 1
        )

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(train=True),
            self.context.get_global_batch_size(),
            shuffle=True,
            seed=self.context.seed,
        )

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(
            self._dataset(train=False),
            self.context.get_global_batch_size(),
            shuffle=False,
            seed=self.context.seed,
        )

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        img = batch["image"]
        return (img, jnp.zeros((img.shape[0],), jnp.int32))

    def loss(
        self, model: UNet, params: Any, batch: Dict[str, jax.Array], rng: jax.Array
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        img = batch["image"].astype(jnp.float32) * 2.0 - 1.0  # [-1, 1]
        timesteps = int(self._hp("timesteps", 1000))
        sched = cosine_schedule(timesteps)
        t_rng, n_rng = jax.random.split(rng)
        t = jax.random.randint(t_rng, (img.shape[0],), 0, timesteps)
        eps = jax.random.normal(n_rng, img.shape)
        x_t = (
            sched["sqrt_ab"][t][:, None, None, None] * img
            + sched["sqrt_1mab"][t][:, None, None, None] * eps
        )
        pred = model.apply(params, x_t, t)
        loss = jnp.mean((pred - eps) ** 2)
        return loss, {"mse": loss}

    def evaluate_batch(
        self, model: UNet, params: Any, batch: Dict[str, jax.Array]
    ) -> Dict[str, jax.Array]:
        # fixed rng -> deterministic validation (same t/noise every epoch)
        loss, _ = self.loss(model, params, batch, jax.random.key(0))
        return {"validation_loss": loss}
