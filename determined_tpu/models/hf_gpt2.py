"""HuggingFace Flax GPT-2 causal-LM fine-tuning trial.

Reference: ``examples/hf_trainer_api`` (HF Trainer + Core API callbacks;
BASELINE.json's north-star names the BERT/GPT-2 fine-tunes).  Like the
BERT family (``hf_bert.py``), the HF **Flax** module drops straight into
the JaxTrial contract — the platform's jitted/donated step, mesh
parallelism, checkpointing and preemption apply to an off-the-shelf
transformers model with a page of glue.

Offline by design: the model initializes from a ``GPT2Config`` (random
weights) and trains on a synthetic Markov-chain language task whose
next-token structure is learnable — TPU pods have no egress.  To
fine-tune real weights, point ``hparams.pretrained_dir`` at a local
``save_pretrained`` directory.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_tpu.data import DataLoader, InMemoryDataset
from determined_tpu.models._hf_common import HFModuleHolder
from determined_tpu.train._trial import JaxTrial


def synthetic_lm(size: int, seq_len: int, vocab: int, seed: int) -> InMemoryDataset:
    """Markov-chain token streams: each token strongly conditions the next
    (one dominant successor per token, from a FIXED permutation shared by
    train/val), so causal-LM loss has real structure to learn and falls
    well below the uniform-vocabulary entropy."""
    fixed = np.random.default_rng(1234)
    successor = fixed.permutation(vocab).astype(np.int32)
    rng = np.random.default_rng(seed)
    ids = np.empty((size, seq_len), np.int32)
    ids[:, 0] = rng.integers(0, vocab, size)
    follow = rng.random((size, seq_len)) < 0.85
    noise = rng.integers(0, vocab, (size, seq_len)).astype(np.int32)
    for t in range(1, seq_len):
        ids[:, t] = np.where(follow[:, t], successor[ids[:, t - 1]], noise[:, t])
    return InMemoryDataset({"input_ids": ids})


class _GPT2Module(HFModuleHolder):
    """Holder wiring GPT-2's forward signature into the shared HF plumbing
    (``_hf_common.HFModuleHolder`` owns the pretrained_dir contract)."""

    @classmethod
    def _model_cls(cls):
        from transformers import FlaxGPT2LMHeadModel

        return FlaxGPT2LMHeadModel

    def _forward_args(self, input_ids):
        b, s = input_ids.shape
        return (
            input_ids,
            jnp.ones_like(input_ids),
            jnp.broadcast_to(jnp.arange(s), (b, s)),
        )


class GPT2FinetuneTrial(JaxTrial):
    """hparams: lr, global_batch_size, seq_len, vocab_size, hidden_size,
    num_layers, num_heads, dataset_size, warmup_steps."""

    def _hp(self, name, default):
        return self.context.get_hparam(name, default)

    def build_model(self) -> _GPT2Module:
        from transformers import GPT2Config

        h = int(self._hp("hidden_size", 128))
        cfg = GPT2Config(
            vocab_size=int(self._hp("vocab_size", 512)),
            n_positions=max(int(self._hp("seq_len", 64)), 64),
            n_embd=h,
            n_layer=int(self._hp("num_layers", 2)),
            n_head=int(self._hp("num_heads", 4)),
            n_inner=4 * h,
        )
        return _GPT2Module(
            cfg, seed=self.context.seed,
            pretrained_dir=str(self._hp("pretrained_dir", "")),
        )

    def build_optimizer(self) -> optax.GradientTransformation:
        lr = float(self._hp("lr", 1e-3))
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, int(self._hp("warmup_steps", 20)), int(self._hp("decay_steps", 2000))
        )
        if self._hp("fused_adamw", False):
            # opt-in only: the A/B on the chip (BASELINE.md r5) measured
            # the optax chain ~0.7% FASTER for this workload — the HF
            # param tree's optimizer share is too small to repay the
            # fused kernel's launch overhead.  Kept as a knob because the
            # semantics match (no clip) and bigger fine-tunes may differ.
            from determined_tpu.ops.fused_adamw import fused_adamw

            return fused_adamw(schedule, weight_decay=0.01, clip_norm=None)
        return optax.adamw(schedule, weight_decay=0.01)

    def _dataset(self, train: bool) -> InMemoryDataset:
        return synthetic_lm(
            size=int(self._hp("dataset_size", 1024)),
            seq_len=int(self._hp("seq_len", 64)),
            vocab=int(self._hp("vocab_size", 512)),
            seed=0 if train else 1,
        )

    def build_training_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(True), self.context.get_global_batch_size(),
                          shuffle=True, seed=self.context.seed)

    def build_validation_data_loader(self) -> DataLoader:
        return DataLoader(self._dataset(False), self.context.get_global_batch_size(),
                          shuffle=False, seed=self.context.seed)

    def model_inputs(self, batch: Dict[str, Any]) -> Tuple[Any, ...]:
        return (jnp.asarray(batch["input_ids"]),)

    def init_params(self, model: _GPT2Module, rng: jax.Array, sample_batch):
        return model.init(rng, jnp.asarray(sample_batch["input_ids"]))

    def _lm_loss(self, logits: jax.Array, ids: jax.Array) -> jax.Array:
        # standard causal shift: predict token t+1 from prefix ..t
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], ids[:, 1:]
        ).mean()

    def loss(self, model: _GPT2Module, params: Any, batch: Dict[str, jax.Array], rng):
        out = model.apply(
            params, batch["input_ids"], deterministic=False, rngs={"dropout": rng}
        )
        loss = self._lm_loss(out.logits, batch["input_ids"])
        return loss, {"perplexity": jnp.exp(loss)}

    def evaluate_batch(self, model: _GPT2Module, params: Any, batch):
        out = model.apply(params, batch["input_ids"], deterministic=True)
        loss = self._lm_loss(out.logits, batch["input_ids"])
        return {"validation_loss": loss, "validation_perplexity": jnp.exp(loss)}
