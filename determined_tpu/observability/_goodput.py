"""Goodput ledger: fold the span timeline into a wall-clock attribution.

Google's ML-goodput methodology (PAPERS.md): before you can fix
non-productive time you have to *attribute* it — init, input, checkpoint,
failure recovery, scheduler wait — against the productive time actually
spent stepping.  This module digests the tracer's Chrome events into that
ledger, per trial and per experiment.

Attribution model (host timeline): spans within one thread nest (they come
from context managers / paired clock reads), so each span's **self time**
is its duration minus its children's.  Self time is bucketed by the span's
category; the self time of the ``trial.run`` wrapper itself — time inside
a trial not covered by any instrumented phase — lands in ``other``, which
is what the ``attributed_pct`` metric penalizes.  Device compute is
attributed through the host-side proxy (step dispatch + the boundary
metric-fetch block, category ``step``); an xplane window
(``profiling.trace``) remains the ground truth for on-device time and can
be lined up with this timeline via the exported wall-clock epoch.

Categories (the ``cat=`` each instrumentation site passes):

- ``step``       step dispatch + boundary block — the productive bucket
- ``compile``    first-call trace+compile of a jitted step
- ``setup``      trainer/model build, sharded init
- ``data``       host-side input wait (and prefetch-worker fetch time)
- ``h2d``        host->device transfer dispatch
- ``checkpoint`` save/drain/stall/finalize
- ``restore``    checkpoint restore (resume replay)
- ``validate``   validation sweeps
- ``scheduler``  slot wait/dispatch (incl. ``gang.dispatch`` — the wait
                 between submitting a trial to the master and its gang
                 holding slots)
- ``rendezvous`` multi-host ``jax.distributed.initialize`` join wait
                 (``exec/run_trial.py``)
- ``remote``     cluster-experiment driver only: the gang's execution
                 window on the master (``gang.remote``) — the ranks' own
                 step/data attribution lives in their per-rank traces
- ``journal``    experiment WAL append+fsync
- ``restart``    supervisor backoff between attempts
- ``other``      uninstrumented remainder inside a trial/experiment span

``gang.teardown`` instants (category ``gang``) mark the master tearing
down and rescheduling a whole gang after one rank died.

``step.comm`` rows: gradient-collective time inside the productive
``step`` bucket, split into exposed (on the critical path) vs hidden
(overlapped with backward compute).  Fed from the Trainer's
``step.comm.{bytes,exposed_us,hidden_us}`` COUNTERS — counters, not
spans, because a synthetic span overlapping the real hot-loop spans would
corrupt the self-time nesting.  The split comes from the bucket-schedule
model in ``train/_overlap.py`` (measured payload bytes over a per-chip
bandwidth table; labeled a model — the xplane op table stays the ground
truth on real chips).  ``dtpu experiment profile`` prints it as the
"exposed comm" line so an overlap win is visible in the profile, not
just the bench.

``step.bubble`` rows: pipe-axis idle time inside the productive ``step``
bucket, from the pipeline schedule's analytic tick model
(``parallel/pipeline.py`` ``BubbleModel`` — (P-1)/(M+P-1) for
gpipe/1f1b, (P-1)/(V*M+P-1) for interleaved).  Same counter mechanism as
``step.comm``: the Trainer reports ``step.bubble.exposed_us`` per report
segment plus static ``step.bubble.{fraction,ticks_total,ticks_idle}``
gauges; ``dtpu experiment profile`` prints the "exposed bubble" line so
a schedule win (interleaved, or 1f1b's memory headroom spent on larger
M) is visible per trial.  Labeled a model — it applies the schedule's
idle fraction to the whole measured step, an upper bound since
embed/head/optimizer time sits outside the schedule.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

PRODUCTIVE_CATS = ("step",)

#: containers whose SELF time is the uninstrumented remainder, not a phase
_WRAPPER_CATS = ("trial", "experiment")

# bf16 peak FLOP/s by TPU generation (public spec sheets); longest-prefix
# matched so "TPU v5 lite" beats the "TPU v5" catch-all.  bench.py uses
# this table for its MFU line; the ledger uses it for mfu_estimate.
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e reports device_kind "TPU v5 lite"
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def chip_peak_flops(device_kind: str, default: float = 197e12) -> float:
    for prefix in sorted(PEAK_FLOPS_BY_KIND, key=len, reverse=True):
        if device_kind.startswith(prefix):
            return PEAK_FLOPS_BY_KIND[prefix]
    return default


def _span_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [
        e
        for e in events
        if e.get("ph") == "X" and isinstance(e.get("dur"), (int, float))
    ]


def _nest(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Annotate a single thread's spans with self time + owning trial.

    Returns records ``{name, cat, ts, dur, self, trial}`` (microseconds).
    Spans are treated as properly nested per thread; the tiny float
    tolerance absorbs clock-read ordering at span boundaries.
    """
    eps = 0.6  # us: adjacent clock reads can collide at our rounding
    out: List[Dict[str, Any]] = []
    stack: List[Dict[str, Any]] = []
    for e in sorted(spans, key=lambda e: (e["ts"], -e["dur"])):
        rec = {
            "name": e["name"],
            "cat": e.get("cat") or "misc",
            "ts": float(e["ts"]),
            "dur": float(e["dur"]),
            "self": float(e["dur"]),
            "trial": (e.get("args") or {}).get("trial"),
        }
        end = rec["ts"] + rec["dur"]
        while stack and rec["ts"] >= stack[-1]["_end"] - eps:
            stack.pop()
        if stack:
            parent = stack[-1]
            parent["self"] = max(parent["self"] - rec["dur"], 0.0)
            if rec["trial"] is None:
                rec["trial"] = parent["trial"]
        rec["_end"] = end
        stack.append(rec)
        out.append(rec)
    for rec in out:
        rec.pop("_end", None)
    return out


def _counter_totals(events: List[Dict[str, Any]]) -> Dict[str, float]:
    totals: Dict[str, float] = {}
    for e in events:
        if e.get("ph") != "C":
            continue
        val = float((e.get("args") or {}).get("value", 0.0))
        if e.get("cat") == "gauge":
            totals[e["name"]] = val
        else:
            totals[e["name"]] = totals.get(e["name"], 0.0) + val
    return totals


def _trial_counters(
    events: List[Dict[str, Any]], trial_windows: Dict[Any, List[Tuple[Any, float, float]]]
) -> Dict[Any, Dict[str, float]]:
    """Per-trial counter totals: a counter event belongs to the trial whose
    ``trial.run`` window (same thread) contains its timestamp."""
    out: Dict[Any, Dict[str, float]] = defaultdict(dict)
    for e in events:
        if e.get("ph") != "C":
            continue
        tid = (e.get("pid", 0), e.get("tid", 0))
        ts = float(e.get("ts") or 0.0)
        trial = (e.get("args") or {}).get("trial")
        if trial is None:
            for rid, t0, t1 in trial_windows.get(tid, ()):
                if t0 <= ts <= t1:
                    trial = rid
                    break
        if trial is None:
            continue
        bucket = out[trial]
        val = float((e.get("args") or {}).get("value", 0.0))
        if e.get("cat") == "gauge":
            bucket[e["name"]] = val
        else:
            bucket[e["name"]] = bucket.get(e["name"], 0.0) + val
    return out


def _comm_entry(
    counters: Dict[str, float], step_us: float
) -> Optional[Dict[str, Any]]:
    """Fold step.comm.* counters into an exposed-vs-hidden comm record
    (None when no comm accounting rode the trace)."""
    exposed_us = counters.get("step.comm.exposed_us")
    if exposed_us is None:
        return None
    hidden_us = counters.get("step.comm.hidden_us", 0.0)
    entry: Dict[str, Any] = {
        "exposed_s": round(exposed_us / 1e6, 6),
        "hidden_s": round(hidden_us / 1e6, 6),
        "exposed_pct_of_step": round(
            100.0 * exposed_us / max(step_us, 1e-9), 2
        ),
        "model": "bucket-schedule-v1",
    }
    if "step.comm.bytes" in counters:
        entry["bytes"] = int(counters["step.comm.bytes"])
    # per-hop (ICI vs DCN) sub-records from the link-aware comm model; a
    # single-hop (pre-multi-slice) trace simply has no such counters
    hops: Dict[str, Any] = {}
    for hop in ("ici", "dcn"):
        hop_exposed = counters.get(f"step.comm.{hop}.exposed_us")
        if hop_exposed is None:
            continue
        hops[hop] = {
            "exposed_s": round(hop_exposed / 1e6, 6),
            "hidden_s": round(
                counters.get(f"step.comm.{hop}.hidden_us", 0.0) / 1e6, 6
            ),
        }
        if f"step.comm.{hop}.bytes" in counters:
            hops[hop]["bytes"] = int(counters[f"step.comm.{hop}.bytes"])
    if hops:
        entry["hops"] = hops
    return entry


def _bubble_entry(
    counters: Dict[str, float], step_us: float
) -> Optional[Dict[str, Any]]:
    """Fold step.bubble.* counters into an exposed-bubble record (None
    when no pipeline schedule rode the trace)."""
    exposed_us = counters.get("step.bubble.exposed_us")
    if exposed_us is None:
        return None
    entry: Dict[str, Any] = {
        "exposed_s": round(exposed_us / 1e6, 6),
        "pct_of_step": round(100.0 * exposed_us / max(step_us, 1e-9), 2),
        "model": "pipeline-tick-v1",
    }
    if "step.bubble.fraction" in counters:
        entry["fraction_modeled"] = round(counters["step.bubble.fraction"], 4)
    if "step.bubble.ticks_total" in counters:
        entry["ticks_total"] = int(counters["step.bubble.ticks_total"])
    if "step.bubble.ticks_idle" in counters:
        entry["ticks_idle"] = int(counters["step.bubble.ticks_idle"])
    return entry


def _breakdown(cat_us: Dict[str, float], denom_us: float) -> Dict[str, Dict[str, float]]:
    denom = max(denom_us, 1e-9)
    return {
        cat: {
            "seconds": round(us / 1e6, 6),
            "pct": round(100.0 * us / denom, 2),
        }
        for cat, us in sorted(cat_us.items(), key=lambda kv: -kv[1])
    }


def _rebase_epochs(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Put events from different processes on one timeline.

    A resumed run appends to the same ``events.jsonl`` from a NEW process
    whose span timestamps are relative to its own monotonic epoch — both
    runs' spans would start near ts=0 and falsely nest.  Each process
    writes a ``clock_sync`` metadata record carrying its wall-clock epoch;
    rebasing shifts every pid's timestamps by its epoch delta from the
    earliest process, so resume gaps and orderings come out real.
    No-op when all events share one pid or no clock_sync is present.
    """
    epochs: Dict[Any, float] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "clock_sync":
            unix = (e.get("args") or {}).get("epoch_unix_s")
            if isinstance(unix, (int, float)):
                epochs.setdefault(e.get("pid"), float(unix))
    if len(epochs) < 2:
        return events
    base = min(epochs.values())
    out = []
    for e in events:
        off = epochs.get(e.get("pid"))
        if off is None or "ts" not in e or e.get("ph") == "M":
            out.append(e)
            continue
        e = dict(e)
        e["ts"] = float(e["ts"]) + (off - base) * 1e6
        out.append(e)
    return out


def compute_ledger(
    events: List[Dict[str, Any]], *, dropped: int = 0
) -> Dict[str, Any]:
    """Digest Chrome trace events into the goodput ledger.

    Returns ``{"experiment": {...}, "trials": {rid: {...}}, "counters",
    "threads", "dropped_events"}``.  ``attributed_pct`` is the share of
    trial wall-clock covered by *named* phases (everything except the
    ``other`` remainder) — the acceptance bar is >= 95.
    """
    events = _rebase_epochs(events)
    spans = _span_events(events)
    # tracks key on (pid, tid): a resumed run's process reuses the same
    # thread idents (MainThread, dtpu-trial-*), which must not merge
    by_tid: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for e in spans:
        by_tid[(e.get("pid", 0), e.get("tid", 0))].append(e)

    exp_wall_us = 0.0
    trial_wall_us: Dict[Any, float] = defaultdict(float)
    trial_cat_us: Dict[Any, Dict[str, float]] = defaultdict(lambda: defaultdict(float))
    thread_cat_us: Dict[Any, Dict[str, float]] = {}
    trial_windows: Dict[Any, List[Tuple[Any, float, float]]] = defaultdict(list)

    for tid, tspans in by_tid.items():
        recs = _nest(tspans)
        cat_us: Dict[str, float] = defaultdict(float)
        for rec in recs:
            cat = rec["cat"]
            if rec["name"] == "experiment.run":
                exp_wall_us += rec["dur"]
            if rec["name"] == "trial.run" and rec["trial"] is not None:
                trial_wall_us[rec["trial"]] += rec["dur"]
                trial_windows[tid].append(
                    (rec["trial"], rec["ts"], rec["ts"] + rec["dur"])
                )
            bucket = "other" if cat in _WRAPPER_CATS else cat
            cat_us[bucket] += rec["self"]
            if rec["trial"] is not None:
                trial_cat_us[rec["trial"]][bucket] += rec["self"]
        thread_cat_us[tid] = dict(cat_us)

    if exp_wall_us <= 0.0 and spans:
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e["dur"] for e in spans)
        exp_wall_us = t1 - t0

    counters = _counter_totals(events)
    per_trial_counters = _trial_counters(events, trial_windows)
    flops_per_token = counters.get("train.flops_per_token")
    peak_flops = counters.get("device.peak_flops_total")

    trials: Dict[Any, Dict[str, Any]] = {}
    total_trial_us = 0.0
    total_attr_us = 0.0
    total_prod_us = 0.0
    agg_cat_us: Dict[str, float] = defaultdict(float)
    for rid, wall in sorted(trial_wall_us.items(), key=lambda kv: str(kv[0])):
        cats = trial_cat_us.get(rid, {})
        attributed = sum(us for c, us in cats.items() if c != "other")
        productive = sum(cats.get(c, 0.0) for c in PRODUCTIVE_CATS)
        tc = per_trial_counters.get(rid, {})
        steps = tc.get("train.steps")
        samples = tc.get("train.samples")
        tokens = tc.get("train.tokens")
        wall_s = wall / 1e6
        entry: Dict[str, Any] = {
            "wall_s": round(wall_s, 6),
            "attributed_pct": round(100.0 * min(attributed / max(wall, 1e-9), 1.0), 2),
            "productive_pct": round(100.0 * min(productive / max(wall, 1e-9), 1.0), 2),
            "breakdown": _breakdown(dict(cats), wall),
        }
        if steps:
            entry["steps"] = int(steps)
        if samples:
            entry["samples"] = int(samples)
            entry["samples_per_s"] = round(samples / max(wall_s, 1e-9), 2)
        if tokens:
            entry["tokens"] = int(tokens)
            entry["tokens_per_s"] = round(tokens / max(wall_s, 1e-9), 2)
            tfpt = tc.get("train.flops_per_token") or flops_per_token
            tpeak = tc.get("device.peak_flops_total") or peak_flops
            if tfpt and tpeak:
                entry["mfu_estimate"] = round(
                    (tokens / max(wall_s, 1e-9)) * tfpt / tpeak, 4
                )
        comm = _comm_entry(tc, cats.get("step", 0.0))
        if comm is not None:
            entry["step.comm"] = comm
        bubble = _bubble_entry(tc, cats.get("step", 0.0))
        if bubble is not None:
            entry["step.bubble"] = bubble
        trials[rid] = entry
        total_trial_us += wall
        total_attr_us += attributed
        total_prod_us += productive
        for c, us in cats.items():
            agg_cat_us[c] += us

    experiment: Dict[str, Any] = {
        "wall_s": round(exp_wall_us / 1e6, 6),
        "trial_seconds": round(total_trial_us / 1e6, 6),
        "attributed_pct": round(
            100.0 * min(total_attr_us / max(total_trial_us, 1e-9), 1.0), 2
        ),
        "productive_pct": round(
            100.0 * min(total_prod_us / max(total_trial_us, 1e-9), 1.0), 2
        ),
        "breakdown": _breakdown(dict(agg_cat_us), total_trial_us),
        "trials": len(trials),
    }
    exp_comm = _comm_entry(counters, agg_cat_us.get("step", 0.0))
    if exp_comm is not None:
        experiment["step.comm"] = exp_comm
    exp_bubble = _bubble_entry(counters, agg_cat_us.get("step", 0.0))
    if exp_bubble is not None:
        experiment["step.bubble"] = exp_bubble
    tokens_total = sum(t.get("tokens", 0) for t in trials.values())
    if tokens_total and total_trial_us > 0:
        experiment["tokens_per_s"] = round(tokens_total / (total_trial_us / 1e6), 2)

    threads = {
        f"{pid}:{tid}": _breakdown(cats, max(sum(cats.values()), 1e-9))
        for (pid, tid), cats in thread_cat_us.items()
    }

    return {
        "experiment": experiment,
        "trials": trials,
        "threads": threads,
        "counters": counters,
        "dropped_events": dropped,
    }


# -- trace loading (the CLI side) --------------------------------------------


def load_trace_events(traces_dir: str) -> List[Dict[str, Any]]:
    """Load Chrome trace events from an experiment's ``traces/`` directory.

    Prefers ``events.jsonl`` (append-only, survives SIGKILL, spans resumed
    runs) and falls back to ``trace.json`` (the finalized export)."""
    jsonl = os.path.join(traces_dir, "events.jsonl")
    if os.path.exists(jsonl):
        events: List[Dict[str, Any]] = []
        with open(jsonl, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # crash-truncated tail line
        return events
    trace = os.path.join(traces_dir, "trace.json")
    if os.path.exists(trace):
        with open(trace, encoding="utf-8") as f:
            return json.load(f).get("traceEvents", [])
    return []


def _comm_line(c: Dict[str, Any]) -> str:
    """The "exposed comm" profile line (docs/performance.md): how much of
    the gradient-collective time sits on the critical path vs hides
    behind backward compute — the number the overlap_grad_sync knob
    exists to shrink.  On a multi-slice trace the link-aware model adds
    one sub-line per hop (ICI vs DCN), so a slow cross-slice hop is
    visible instead of averaged into one number."""
    line = (
        f"  exposed comm {c['exposed_s']:>10.3f}s "
        f"({c['exposed_pct_of_step']:.1f}% of step; "
        f"hidden {c['hidden_s']:.3f}s) [{c['model']}]"
    )
    for hop, h in c.get("hops", {}).items():
        size = f", {h['bytes'] / 1e9:.2f} GB" if "bytes" in h else ""
        line += (
            f"\n    {hop:<4} exposed {h['exposed_s']:>8.3f}s "
            f"(hidden {h['hidden_s']:.3f}s{size})"
        )
    return line


def _bubble_line(b: Dict[str, Any]) -> str:
    """The "exposed bubble" profile line (docs/performance.md): how much
    of the step the pipeline schedule's analytic tick model attributes to
    pipe-axis idle time — the number the 1f1b/interleaved schedules exist
    to shrink."""
    frac = b.get("fraction_modeled")
    ticks = (
        f"; {b['ticks_idle']}/{b['ticks_total']} ticks idle"
        if "ticks_total" in b and "ticks_idle" in b
        else ""
    )
    detail = f" (modeled {100.0 * frac:.1f}%{ticks})" if frac is not None else ""
    return (
        f"  exposed bubble {b['exposed_s']:>8.3f}s "
        f"({b['pct_of_step']:.1f}% of step){detail} [{b['model']}]"
    )


def format_ledger_text(ledger: Dict[str, Any]) -> str:
    """Human-readable ledger (the ``dtpu experiment profile`` text view)."""
    exp = ledger["experiment"]
    lines = [
        f"experiment wall-clock: {exp['wall_s']:.2f}s over {exp['trials']} trial(s) "
        f"({exp['trial_seconds']:.2f} trial-seconds)",
        f"attributed: {exp['attributed_pct']:.1f}%   "
        f"productive (step): {exp['productive_pct']:.1f}%",
    ]
    if "tokens_per_s" in exp:
        lines.append(f"tokens/s (per trial-second): {exp['tokens_per_s']:.1f}")
    lines.append("")
    lines.append("phase breakdown (% of trial-seconds):")
    for cat, row in exp["breakdown"].items():
        lines.append(f"  {cat:<12} {row['seconds']:>10.3f}s  {row['pct']:>6.2f}%")
    if "step.comm" in exp:
        lines.append(_comm_line(exp["step.comm"]))
    if "step.bubble" in exp:
        lines.append(_bubble_line(exp["step.bubble"]))
    for rid, t in ledger["trials"].items():
        lines.append("")
        head = (
            f"trial {rid}: {t['wall_s']:.2f}s  attributed {t['attributed_pct']:.1f}%"
            f"  productive {t['productive_pct']:.1f}%"
        )
        extras = []
        if "steps" in t:
            extras.append(f"{t['steps']} steps")
        if "samples_per_s" in t:
            extras.append(f"{t['samples_per_s']:.1f} samples/s")
        if "tokens_per_s" in t:
            extras.append(f"{t['tokens_per_s']:.1f} tokens/s")
        if "mfu_estimate" in t:
            extras.append(f"mfu~{t['mfu_estimate']:.3f}")
        if extras:
            head += "  (" + ", ".join(extras) + ")"
        lines.append(head)
        for cat, row in t["breakdown"].items():
            lines.append(f"  {cat:<12} {row['seconds']:>10.3f}s  {row['pct']:>6.2f}%")
        if "step.comm" in t:
            lines.append(_comm_line(t["step.comm"]))
        if "step.bubble" in t:
            lines.append(_bubble_line(t["step.bubble"]))
    if ledger.get("dropped_events"):
        lines.append("")
        lines.append(
            f"WARNING: {ledger['dropped_events']} events dropped (ring overflow); "
            "percentages under-count the busiest phases"
        )
    return "\n".join(lines)
