"""Observability: experiment-wide tracing + the goodput ledger.

Every concurrent subsystem reports spans/counters into the process tracer
(``get_tracer()``); the timeline exports as Chrome trace-event JSON under
``checkpoint_dir/traces/`` (viewable in Perfetto) and folds into a goodput
ledger attributing every second of wall-clock to a named phase
(``dtpu experiment profile <dir>``).  See ``docs/observability.md``.

The hot-path contract: recording never locks, never blocks, never syncs
the host; a disabled tracer costs one attribute check.
"""

from determined_tpu.observability._goodput import (
    PEAK_FLOPS_BY_KIND,
    PRODUCTIVE_CATS,
    chip_peak_flops,
    compute_ledger,
    format_ledger_text,
    load_trace_events,
)
from determined_tpu.observability._tracer import Tracer, get_tracer

__all__ = [
    "PEAK_FLOPS_BY_KIND",
    "PRODUCTIVE_CATS",
    "Tracer",
    "chip_peak_flops",
    "compute_ledger",
    "export_experiment_trace",
    "format_ledger_text",
    "get_tracer",
    "load_trace_events",
]


def export_experiment_trace(tracer, out_dir: str) -> dict:
    """Finalize an experiment's trace: write ``trace.json`` (Perfetto) and
    ``goodput.json`` (the ledger) under ``out_dir``.  Returns the ledger."""
    import json
    import os

    trace_path = tracer.export_chrome_trace(os.path.join(out_dir, "trace.json"))
    ledger = compute_ledger(tracer.chrome_events(), dropped=tracer.dropped())
    ledger_path = os.path.join(out_dir, "goodput.json")
    tmp = ledger_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(ledger, f, indent=2, sort_keys=True, default=str)
    os.replace(tmp, ledger_path)
    ledger["trace_path"] = trace_path
    return ledger
