"""Experiment-wide tracer: thread-safe, non-blocking spans and counters.

BENCH rounds 2-5 sat flat at ~0.70 MFU with no way to see where a step's
wall-clock actually went — data wait vs. device compute vs. checkpoint
stall vs. scheduler slot wait vs. restart replay.  This module is the
attribution layer: every concurrent subsystem (trainer loop, prefetch
workers, trial scheduler, journal, checkpoint writers, restart supervisor)
reports spans/counters here, and the whole concurrent search becomes one
Chrome trace-event timeline viewable in Perfetto plus a goodput ledger
(``_goodput.py``).

Design constraints, in order:

1. **Never a host sync or a lock in the hot loop.**  Each thread records
   into its OWN fixed-size ring buffer (single producer).  Recording is a
   ``time.monotonic()`` delta plus one tuple append — no allocation beyond
   the tuple, no lock, no I/O.  A full ring DROPS the event and counts the
   drop; it never blocks training.
2. **~0 cost when off.**  ``enabled`` is a single attribute check;
   ``span()`` returns a shared no-op context manager.
3. **Draining is someone else's problem.**  A shipper thread (the
   ``MetricsContext`` pattern, ``core/_metrics.py``) drains all rings on a
   short interval, converts tuples to Chrome trace events, and — when
   export is configured — appends them as JSONL under
   ``<out_dir>/events.jsonl`` so even a SIGKILLed run leaves a readable
   timeline.  ``export_chrome_trace`` writes the standard
   ``{"traceEvents": [...]}`` JSON that Perfetto/chrome://tracing load.

Clocks: span timestamps are ``time.monotonic()`` relative to a per-process
epoch; the matching ``time.time()`` wall epoch is stored in the trace
metadata so a sampled ``jax.profiler`` xplane window can be lined up with
the span timeline.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("determined_tpu.observability")

# Event tuples pushed into the per-thread rings (the hot-path format; the
# drain side converts to Chrome trace-event dicts):
#   ("X", name, cat, t0, dur_s, args)    complete span (monotonic seconds)
#   ("I", name, cat, t, args)            instant event
#   ("C", name, t, value, kind, args)    counter (kind "c": accumulates)
#                                        or gauge (kind "g": last wins)

DEFAULT_RING_CAPACITY = 8192
DEFAULT_FLUSH_INTERVAL = 0.5
DEFAULT_MAX_EVENTS = 1_000_000


class _Ring:
    """Single-producer / single-consumer ring of event tuples.

    Lock-free under the GIL: the producer (the owning thread) writes the
    slot and then publishes it by incrementing ``tail`` — an int store the
    GIL makes atomic; the consumer (the tracer's drain, serialized by the
    tracer lock) snapshots ``tail`` and reads only slots below it.  A full
    ring drops (counted in ``dropped``) instead of blocking: observability
    must never back-pressure training.
    """

    __slots__ = ("items", "capacity", "head", "tail", "dropped", "tid",
                 "thread_name", "thread")

    def __init__(self, capacity: int, owner: threading.Thread) -> None:
        self.items: List[Any] = [None] * capacity
        self.capacity = capacity
        self.head = 0  # consumer cursor: only drain() advances it
        self.tail = 0  # producer cursor: only push() advances it
        self.dropped = 0
        self.tid = owner.ident or id(owner)
        self.thread_name = owner.name
        self.thread = owner  # drained-empty rings of dead threads get pruned

    def push(self, item: Tuple) -> bool:
        # producer-only state; see class docstring for the SPSC argument
        if self.tail - self.head >= self.capacity:
            self.dropped += 1  # dtpu: lint-ok[unlocked-shared-state]
            return False
        self.items[self.tail % self.capacity] = item
        self.tail += 1  # dtpu: lint-ok[unlocked-shared-state]
        return True

    def drain(self) -> List[Tuple]:
        # consumer-only; callers serialize via the tracer lock
        out: List[Tuple] = []
        tail = self.tail  # snapshot: everything below is fully written
        head = self.head
        while head < tail:
            i = head % self.capacity
            out.append(self.items[i])
            self.items[i] = None
            head += 1
        self.head = head
        return out


class _Span:
    """Context-manager span bound to one tracer."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Optional[Dict]) -> None:
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self._tracer.record_span(
            self._name, self._cat, self._t0, time.monotonic(), self._args
        )
        return False


class _NullSpan:
    """Shared do-nothing span: what ``span()`` returns when disabled."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Process-wide span/counter sink with per-thread ring buffers.

    All recording methods are safe from any thread and never block; the
    drain/export side serializes on one internal lock.  One tracer serves
    the whole process (``get_tracer()``) — concurrent trials distinguish
    themselves by thread and by the ``trial`` span argument.
    """

    def __init__(
        self,
        *,
        ring_capacity: int = DEFAULT_RING_CAPACITY,
        flush_interval: float = DEFAULT_FLUSH_INTERVAL,
        max_events: int = DEFAULT_MAX_EVENTS,
    ) -> None:
        self.enabled = True
        self._epoch = time.monotonic()
        self._epoch_wall = time.time()
        self._ring_capacity = ring_capacity
        self._flush_interval = flush_interval
        self._max_events = max_events
        self._local = threading.local()
        # guards everything below (registry, drained events, counters,
        # export handle, shipper lifecycle)
        self._lock = threading.Lock()
        self._rings: Dict[int, _Ring] = {}
        self._events: List[Dict[str, Any]] = []
        self._events_dropped = 0
        self._counters: Dict[str, float] = {}
        self._named_tids: set = set()
        self._out_dir: Optional[str] = None
        self._jsonl: Optional[Any] = None
        self._shipper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pid = os.getpid()

    # -- configuration -----------------------------------------------------

    def configure(
        self,
        *,
        out_dir: Optional[str] = None,
        ring_capacity: Optional[int] = None,
        flush_interval: Optional[float] = None,
        max_events: Optional[int] = None,
        enabled: Optional[bool] = None,
    ) -> "Tracer":
        """(Re)configure the tracer — called by the experiment runner and
        bench before any trial thread starts.  ``out_dir`` turns on JSONL
        export (``<out_dir>/events.jsonl``, append: resumed runs extend
        the same timeline)."""
        with self._lock:
            if ring_capacity is not None:
                self._ring_capacity = int(ring_capacity)
            if flush_interval is not None:
                self._flush_interval = float(flush_interval)
            if max_events is not None:
                self._max_events = int(max_events)
            if enabled is not None:
                self.enabled = bool(enabled)
            if out_dir != self._out_dir:
                if self._jsonl is not None:
                    self._jsonl.close()
                    self._jsonl = None
                self._out_dir = out_dir
                if out_dir is not None:
                    os.makedirs(out_dir, exist_ok=True)
                    self._jsonl = open(
                        os.path.join(out_dir, "events.jsonl"), "a", encoding="utf-8"
                    )
                    meta = {
                        "ph": "M",
                        "name": "clock_sync",
                        "pid": self._pid,
                        "tid": 0,
                        "ts": 0,
                        "args": {
                            "epoch_unix_s": self._epoch_wall,
                            "epoch_monotonic_s": self._epoch,
                        },
                    }
                    self._jsonl.write(json.dumps(meta) + "\n")
                    self._jsonl.flush()
        return self

    def reset(self) -> None:
        """Drop drained events/counters (a new experiment's clean slate).
        Ring registrations survive — live threads keep their buffers."""
        self.drain()
        with self._lock:
            self._events = []
            self._events_dropped = 0
            self._counters = {}
            self._named_tids = set()
            for ring in self._rings.values():
                # the clean slate covers drop counts too, or a new run
                # would warn about the previous run's ring overflows
                ring.dropped = 0

    # -- hot-path recording ------------------------------------------------

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None or ring.capacity != self._ring_capacity:
            ring = _Ring(self._ring_capacity, threading.current_thread())
            self._local.ring = ring
            with self._lock:
                # keyed by object id: a recycled thread ident must not
                # replace a dead thread's ring before its tail is drained
                self._rings[id(ring)] = ring
        return ring

    def record_span(
        self,
        name: str,
        cat: str,
        t0: float,
        t1: float,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record an already-timed span (``time.monotonic()`` endpoints).
        The hot-loop form: two clock reads + one tuple push."""
        if not self.enabled:
            return
        self._ring().push(("X", name, cat, t0, t1 - t0, args))

    def span(self, name: str, cat: str = "misc", **args: Any) -> Any:
        """Context-manager span; ~free when the tracer is disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "misc", **args: Any) -> None:
        if not self.enabled:
            return
        self._ring().push(("I", name, cat, time.monotonic(), args or None))

    def counter(self, name: str, value: float = 1.0, **args: Any) -> None:
        """Accumulating counter (drain sums values)."""
        if not self.enabled:
            return
        self._ring().push(("C", name, time.monotonic(), value, "c", args or None))

    def gauge(self, name: str, value: float, **args: Any) -> None:
        """Point-in-time gauge (drain keeps the last value)."""
        if not self.enabled:
            return
        self._ring().push(("C", name, time.monotonic(), value, "g", args or None))

    # -- drain / shipper ---------------------------------------------------

    def _to_us(self, t: float) -> float:
        return round((t - self._epoch) * 1e6, 1)

    def _convert(self, ring: _Ring, item: Tuple) -> Dict[str, Any]:
        kind = item[0]
        if kind == "X":
            _, name, cat, t0, dur, args = item
            ev = {
                "ph": "X",
                "name": name,
                "cat": cat or "misc",
                "ts": self._to_us(t0),
                "dur": round(dur * 1e6, 1),
                "pid": self._pid,
                "tid": ring.tid,
            }
            if args:
                ev["args"] = args
            return ev
        if kind == "I":
            _, name, cat, t, args = item
            ev = {
                "ph": "i",
                "s": "t",
                "name": name,
                "cat": cat or "misc",
                "ts": self._to_us(t),
                "pid": self._pid,
                "tid": ring.tid,
            }
            if args:
                ev["args"] = args
            return ev
        # "C"
        _, name, t, value, ckind, args = item
        ev = {
            "ph": "C",
            "name": name,
            "ts": self._to_us(t),
            "pid": self._pid,
            "tid": ring.tid,
            "args": {"value": value},
        }
        if args:
            ev["args"].update(args)
        ev["cat"] = "counter" if ckind == "c" else "gauge"
        return ev

    def drain(self) -> int:
        """Move every ring's pending events into the drained list (and the
        JSONL export when configured).  Returns how many events moved.
        Safe from any thread; serialized internally."""
        moved = 0
        with self._lock:
            lines: List[str] = []
            for key, ring in list(self._rings.items()):
                items = ring.drain()
                if not items:
                    # fully drained ring of a dead thread: prune it, or a
                    # long search's finished trial/worker threads would
                    # accumulate 8192-slot buffers for the process lifetime
                    # (its drop count must survive the prune)
                    if ring.head == ring.tail and not ring.thread.is_alive():
                        self._events_dropped += ring.dropped
                        del self._rings[key]
                    continue
                if ring.tid not in self._named_tids:
                    self._named_tids.add(ring.tid)
                    name_ev = {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": self._pid,
                        "tid": ring.tid,
                        "ts": 0,
                        "args": {"name": ring.thread_name},
                    }
                    self._append_event(name_ev, lines)
                for item in items:
                    ev = self._convert(ring, item)
                    if ev["ph"] == "C":
                        val = float(ev["args"]["value"])
                        if ev.get("cat") == "gauge":
                            self._counters[ev["name"]] = val
                        else:
                            self._counters[ev["name"]] = (
                                self._counters.get(ev["name"], 0.0) + val
                            )
                    self._append_event(ev, lines)
                    moved += 1
            if lines and self._jsonl is not None:
                try:
                    self._jsonl.write("".join(lines))
                    self._jsonl.flush()
                except OSError:
                    logger.exception("trace export write failed; export disabled")
                    self._jsonl = None
        return moved

    def _append_event(self, ev: Dict[str, Any], lines: List[str]) -> None:
        # Safe: every caller (drain) already holds self._lock — the lint
        # pass can't see a lock held across a method boundary.
        if len(self._events) < self._max_events:
            self._events.append(ev)  # dtpu: lint-ok[unlocked-shared-state]
        else:
            self._events_dropped += 1  # dtpu: lint-ok[unlocked-shared-state]
        if self._jsonl is not None:
            lines.append(json.dumps(ev, default=str) + "\n")

    def _run(self) -> None:
        while not self._stop.wait(self._flush_interval):
            try:
                self.drain()
            except Exception:  # noqa: BLE001 - the shipper must survive
                logger.exception("trace drain failed")

    def start(self) -> "Tracer":
        """Start the background shipper (idempotent)."""
        with self._lock:
            if self._shipper is not None:
                return self
            self._stop.clear()
            self._shipper = threading.Thread(
                target=self._run, name="dtpu-obs-shipper", daemon=True
            )
            self._shipper.start()
        return self

    def stop(self) -> None:
        """Stop the shipper and perform a final drain.  Idempotent."""
        with self._lock:
            shipper, self._shipper = self._shipper, None
        if shipper is not None:
            self._stop.set()
            shipper.join(timeout=10)
        self.drain()

    # -- inspection / export -----------------------------------------------

    @property
    def epoch_wall(self) -> float:
        return self._epoch_wall

    def chrome_events(self) -> List[Dict[str, Any]]:
        """Snapshot of all drained events (drains first)."""
        self.drain()
        with self._lock:
            return list(self._events)

    def counters(self) -> Dict[str, float]:
        self.drain()
        with self._lock:
            return dict(self._counters)

    def stats(self) -> Dict[str, Any]:
        self.drain()
        with self._lock:
            ring_dropped = sum(r.dropped for r in self._rings.values())
            return {
                "events": len(self._events),
                "dropped": ring_dropped + self._events_dropped,
                "ring_dropped": ring_dropped,
                "threads": len(self._rings),
                "counters": dict(self._counters),
            }

    def dropped(self) -> int:
        with self._lock:
            return sum(r.dropped for r in self._rings.values()) + self._events_dropped

    def export_chrome_trace(self, path: str) -> str:
        """Write a self-contained ``{"traceEvents": [...]}`` JSON file
        (the format Perfetto / chrome://tracing load directly)."""
        events = self.chrome_events()
        with self._lock:
            named = set()
            meta: List[Dict[str, Any]] = []
            for ring in self._rings.values():
                if ring.tid in named:
                    continue
                named.add(ring.tid)
                meta.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": self._pid,
                        "tid": ring.tid,
                        "ts": 0,
                        "args": {"name": ring.thread_name},
                    }
                )
            payload = {
                "traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "epoch_unix_s": self._epoch_wall,
                    "epoch_monotonic_s": self._epoch,
                    "dropped_events": self._events_dropped
                    + sum(r.dropped for r in self._rings.values()),
                },
            }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        self.stop()
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None
            self._out_dir = None


# Process-global tracer: trainer, prefetch workers, scheduler, journal and
# supervisor all record here; the experiment runner owns its lifecycle.
_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer
