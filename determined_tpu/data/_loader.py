"""DataLoader: deterministic, shard-aware, resumable batch stream.

Replaces the reference's ``pytorch.DataLoader`` wrapper
(``harness/determined/pytorch/_data.py``) with a TPU-first design:

- host-side batches are numpy; ``to_global`` forms a **global jax.Array**
  sharded over the mesh batch axes via
  ``jax.make_array_from_process_local_data`` — the multi-host input path.
- iteration state (epoch, batch) is a tiny dict, checkpointed with the
  trial (reference stores dataset offsets the same way,
  ``_pytorch_trial.py:1088``).
- all batches are full-size (static shapes for XLA).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.data._dataset import Dataset, InMemoryDataset
from determined_tpu.data._sampler import IndexSampler, SamplerState
from determined_tpu.parallel.mesh import MeshAxes


def _fetch(dataset: Dataset, indices: np.ndarray) -> Dict[str, np.ndarray]:
    if isinstance(dataset, InMemoryDataset):
        return dataset.gather(indices)
    items = [dataset[int(i)] for i in indices]
    return {k: np.stack([it[k] for it in items]) for k in items[0]}


class DataLoader:
    """Deterministic batch stream over a map-style Dataset.

    ``shard_rank``/``num_shards`` default to this process's position among
    the data-feeding processes (one shard per host process).
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        shard_rank: Optional[int] = None,
        num_shards: Optional[int] = None,
    ) -> None:
        self.dataset = dataset
        if shard_rank is None:
            shard_rank = jax.process_index()
        if num_shards is None:
            num_shards = jax.process_count()
        self.sampler = IndexSampler(
            len(dataset),
            batch_size,
            shard_rank=shard_rank,
            num_shards=num_shards,
            shuffle=shuffle,
            seed=seed,
        )
        self._state = SamplerState()

    # -- resume state ------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self._state.epoch, "batches_in_epoch": self._state.batches_in_epoch}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self._state = SamplerState(int(state["epoch"]), int(state["batches_in_epoch"]))

    @property
    def batches_per_epoch(self) -> int:
        return self.sampler.batches_per_epoch

    @property
    def epoch(self) -> int:
        return self._state.epoch

    # -- iteration ---------------------------------------------------------

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        """Infinite stream of host-local batches, advancing resume state."""
        for state, idx in self.sampler.iter_from(self._state):
            batch = _fetch(self.dataset, idx)
            self._state = state
            yield batch

    def iter_epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """One full pass (e.g. a validation sweep); resume state untouched."""
        batches = self.sampler.epoch_batches(epoch)
        for b in range(self.sampler.batches_per_epoch):
            yield _fetch(self.dataset, batches[b])


def batch_spec(mesh: Mesh, ndim: int) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over every batch-carrying mesh axis."""
    batch_axes = tuple(a for a in (MeshAxes.DATA, MeshAxes.FSDP) if mesh.shape.get(a, 1) > 1)
    first = batch_axes if batch_axes else None
    return PartitionSpec(first, *([None] * (ndim - 1)))


def to_global(
    batch: Dict[str, np.ndarray], mesh: Mesh, micro_dim: bool = False
) -> Dict[str, jax.Array]:
    """Assemble per-process local batches into global, batch-sharded arrays.

    Single-process (incl. the 8-virtual-device CPU mesh): the local batch IS
    the global batch; multi-host: each process contributes its shard.
    ``micro_dim``: leaves are stacked microbatches ``[agg, batch, ...]``
    (gradient accumulation) — the batch axes shard dim 1, dim 0 replicates.
    """
    out: Dict[str, jax.Array] = {}
    for k, v in batch.items():
        spec = batch_spec(mesh, v.ndim - 1 if micro_dim else v.ndim)
        if micro_dim:
            spec = PartitionSpec(None, *spec)
        sharding = NamedSharding(mesh, spec)
        out[k] = jax.make_array_from_process_local_data(sharding, v)
    return out
