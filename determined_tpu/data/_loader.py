"""DataLoader: deterministic, shard-aware, resumable batch stream.

Replaces the reference's ``pytorch.DataLoader`` wrapper
(``harness/determined/pytorch/_data.py``) with a TPU-first design:

- host-side batches are numpy; ``to_global`` forms a **global jax.Array**
  sharded over the mesh batch axes via
  ``jax.make_array_from_process_local_data`` — the multi-host input path.
- iteration state (epoch, batch) is a tiny dict, checkpointed with the
  trial (reference stores dataset offsets the same way,
  ``_pytorch_trial.py:1088``).
- all batches are full-size (static shapes for XLA).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from determined_tpu.data._dataset import Dataset, InMemoryDataset
from determined_tpu.data._sampler import IndexSampler, SamplerState
from determined_tpu.parallel.mesh import MeshAxes


def _fetch(
    dataset: Dataset, indices: np.ndarray, pool: Optional[Any] = None
) -> Dict[str, np.ndarray]:
    if isinstance(dataset, InMemoryDataset):
        return dataset.gather(indices)
    # map-style dataset: the per-item loop is the slow path (disk reads,
    # decode); a thread pool overlaps the item I/O when the loader has a
    # `fetch_workers` budget
    idx = [int(i) for i in indices]
    if pool is not None:
        items = list(pool.map(dataset.__getitem__, idx))
    else:
        items = [dataset[i] for i in idx]
    keys = list(items[0])
    if len(keys) == 1:
        # single-key short-circuit: skip the per-key comprehension and the
        # repeated item-dict walks; np.stack semantics (raise on ragged,
        # promote on dtype mismatch) are kept by construction
        k = keys[0]
        return {k: np.stack([it[k] for it in items])}
    return {k: np.stack([it[k] for it in items]) for k in keys}


class DataLoader:
    """Deterministic batch stream over a map-style Dataset.

    ``shard_rank``/``num_shards`` default to this process's position among
    the data-feeding processes (one shard per host process).
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        shard_rank: Optional[int] = None,
        num_shards: Optional[int] = None,
        fetch_workers: int = 0,
    ) -> None:
        self.dataset = dataset
        self.fetch_workers = fetch_workers
        self._pool: Optional[Any] = None
        if shard_rank is None:
            shard_rank = jax.process_index()
        if num_shards is None:
            num_shards = jax.process_count()
        self.sampler = IndexSampler(
            len(dataset),
            batch_size,
            shard_rank=shard_rank,
            num_shards=num_shards,
            shuffle=shuffle,
            seed=seed,
        )
        self._state = SamplerState()

    # -- resume state ------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        # global_batch makes the consumed position portable across an elastic
        # reshard: batch order is shard-count independent (shuffle -> batch
        # globally -> shard), so with a constant global batch the position
        # transfers verbatim; if the global batch changed, load_state_dict
        # rescales sample-for-sample.
        return {
            "epoch": self._state.epoch,
            "batches_in_epoch": self._state.batches_in_epoch,
            "global_batch": self.sampler.global_batch,
        }

    def load_state_dict(self, state: Dict[str, int]) -> None:
        epoch = int(state["epoch"])
        batches = int(state["batches_in_epoch"])
        stored_gb = int(state.get("global_batch", self.sampler.global_batch))
        if stored_gb != self.sampler.global_batch:
            # Re-express the consumed position in new-global-batch units.
            # Round down: a partially-covered batch is re-trained rather than
            # skipped (never drop a sample; double-training is bounded by one
            # batch and only occurs when the global batch itself changed).
            consumed = batches * stored_gb
            batches = consumed // self.sampler.global_batch
            batches = min(batches, self.sampler.batches_per_epoch)
        self._state = SamplerState(epoch, batches)

    @property
    def batches_per_epoch(self) -> int:
        return self.sampler.batches_per_epoch

    @property
    def epoch(self) -> int:
        return self._state.epoch

    # -- iteration ---------------------------------------------------------

    def _fetch_pool(self) -> Optional[Any]:
        """Lazily built thread pool for the map-style fetch path.  Only
        non-InMemory datasets ever touch it (the columnar gather needs no
        threads), so construction waits for the first such fetch."""
        if self.fetch_workers and self.fetch_workers > 0 and self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=int(self.fetch_workers),
                thread_name_prefix="dtpu-fetch",
            )
        return self._pool

    def _fetch_batch(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        pool = None if isinstance(self.dataset, InMemoryDataset) else self._fetch_pool()
        return _fetch(self.dataset, idx, pool)

    def close(self) -> None:
        """Release the fetch pool (if one was built).  The loader stays
        usable — a later fetch lazily rebuilds it."""
        if self._pool is not None:
            # cancel_futures: a preempted trial must not sit in the atexit
            # join while queued slow item reads of an abandoned batch drain
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        """Infinite stream of host-local batches, advancing resume state."""
        for state, idx in self.sampler.iter_from(self._state):
            batch = self._fetch_batch(idx)
            self._state = state
            yield batch

    def iter_pairs(
        self, agg: int = 1
    ) -> Iterator[Tuple[SamplerState, Dict[str, np.ndarray]]]:
        """Infinite ``(state_after, batch)`` stream; does NOT advance the
        loader's resume state — the consumer commits via ``commit_state``
        when it actually takes the batch (the prefetch pipeline's
        consumed-vs-fetched invariant, ``data/_prefetch.py``).

        ``agg`` > 1 groups that many microbatches into one stacked
        ``[agg, batch, ...]`` batch (gradient accumulation); the state is
        that after the LAST microbatch, so one optimizer step = one commit.
        """
        it = self.sampler.iter_from(self._state)
        if agg <= 1:
            for state, idx in it:
                yield state, self._fetch_batch(idx)
            return
        while True:
            micros = []
            for _ in range(agg):
                state, idx = next(it)
                micros.append(self._fetch_batch(idx))
            yield state, {k: np.stack([m[k] for m in micros]) for k in micros[0]}

    def commit_state(self, state: SamplerState) -> None:
        """Record that the consumer has taken every batch up to ``state``."""
        self._state = state

    def iter_epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """One full pass (e.g. a validation sweep); resume state untouched."""
        batches = self.sampler.epoch_batches(epoch)
        for b in range(self.sampler.batches_per_epoch):
            yield self._fetch_batch(batches[b])


def batch_spec(mesh: Mesh, ndim: int) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over every batch-carrying mesh axis."""
    batch_axes = tuple(a for a in MeshAxes.BATCH_AXES if mesh.shape.get(a, 1) > 1)
    first = batch_axes if batch_axes else None
    return PartitionSpec(first, *([None] * (ndim - 1)))


@functools.lru_cache(maxsize=64)
def cached_batch_sharding(mesh: Mesh, ndim: int, micro_dim: bool) -> NamedSharding:
    """The NamedSharding ``to_global`` uses for a rank-``ndim`` leaf.

    Building a PartitionSpec + NamedSharding per key per step is pure
    overhead on the input hot path — the result depends only on
    ``(mesh, ndim, micro_dim)``, so it is memoized (Mesh is hashable, and
    a trial touches a handful of (mesh, ndim) combinations for its
    lifetime).
    """
    spec = batch_spec(mesh, ndim - 1 if micro_dim else ndim)
    if micro_dim:
        spec = PartitionSpec(None, *spec)
    return NamedSharding(mesh, spec)


def to_global(
    batch: Dict[str, np.ndarray], mesh: Mesh, micro_dim: bool = False
) -> Dict[str, jax.Array]:
    """Assemble per-process local batches into global, batch-sharded arrays.

    Single-process (incl. the 8-virtual-device CPU mesh): the local batch IS
    the global batch; multi-host: each process contributes its shard.
    ``micro_dim``: leaves are stacked microbatches ``[agg, batch, ...]``
    (gradient accumulation) — the batch axes shard dim 1, dim 0 replicates.
    """
    out: Dict[str, jax.Array] = {}
    for k, v in batch.items():
        sharding = cached_batch_sharding(mesh, v.ndim, micro_dim)
        out[k] = jax.make_array_from_process_local_data(sharding, v)
    return out
