"""Dataset protocol + in-memory/synthetic datasets.

The reference wraps ``torch.utils.data`` (``harness/determined/pytorch/_data.py``)
— datasets are map-style objects with ``__len__``/``__getitem__``.  Here the
same protocol is kept, but items are **dicts of numpy arrays** so batches
stack into host arrays that convert straight into (sharded) ``jax.Array``s.

Static shapes are a hard requirement on TPU (XLA retraces on shape change),
so batching always drops ragged tails (``drop_last`` semantics).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Any, Dict, Protocol, Sequence, runtime_checkable

import numpy as np


@runtime_checkable
class Dataset(Protocol):
    def __len__(self) -> int: ...

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]: ...


class InMemoryDataset:
    """Columnar dict-of-arrays dataset; the fast path for TPU input
    pipelines (whole-shard gather by fancy indexing, no per-item loop)."""

    def __init__(self, columns: Dict[str, np.ndarray]) -> None:
        if not columns:
            raise ValueError("InMemoryDataset needs at least one column")
        lengths = {k: len(v) for k, v in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"column lengths differ: {lengths}")
        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        self._len = next(iter(lengths.values()))

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx: int) -> Dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.columns.items()}

    def gather(self, indices: np.ndarray) -> Dict[str, np.ndarray]:
        """Vectorized batch fetch — one fancy-index per column."""
        return {k: v[indices] for k, v in self.columns.items()}


class SyntheticDataset(InMemoryDataset):
    """Deterministic random dataset for tests/benchmarks (the analog of the
    reference's noop/onevar fixtures, ``harness/tests/experiment/fixtures/``)."""

    def __init__(
        self,
        spec: Dict[str, Any],
        size: int,
        seed: int = 0,
    ) -> None:
        """spec: name -> (shape, dtype) or (shape, dtype, num_classes) for ints."""
        rng = np.random.default_rng(seed)
        cols: Dict[str, np.ndarray] = {}
        for name, s in spec.items():
            shape = (size, *s[0])
            dtype = np.dtype(s[1])
            if np.issubdtype(dtype, np.integer):
                hi = s[2] if len(s) > 2 else 2
                cols[name] = rng.integers(0, hi, size=shape, dtype=dtype)
            else:
                cols[name] = rng.standard_normal(shape).astype(dtype)
        super().__init__(cols)


def mnist_like(
    size: int = 4096, image_key: str = "image", label_key: str = "label", seed: int = 0
) -> InMemoryDataset:
    """MNIST-shaped dataset. Loads the real IDX files if present locally
    (no network egress on TPU pods), else a class-separable synthetic set so
    accuracy actually improves during tests.
    """
    for root in (
        os.environ.get("DTPU_MNIST_DIR", ""),
        "/root/data/mnist",
        os.path.expanduser("~/.cache/mnist"),
    ):
        if root and os.path.exists(os.path.join(root, "train-images-idx3-ubyte.gz")):
            # seed selects a disjoint slice so train (seed 0) and val
            # (seed 1) never overlap on real data either.
            return InMemoryDataset(
                _load_idx_mnist(root, size, image_key, label_key, offset=seed * size)
            )
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=size, dtype=np.int32)
    # Class-separable images: per-class template + noise.  Templates come
    # from a FIXED generator so train/val splits (different seeds) share the
    # same label->image mapping and accuracy is meaningful.
    templates = np.random.default_rng(1234).standard_normal((10, 28, 28)).astype(np.float32)
    images = templates[labels] + 0.3 * rng.standard_normal((size, 28, 28)).astype(np.float32)
    return InMemoryDataset({image_key: images[..., None], label_key: labels})


def _load_idx_mnist(
    root: str, size: int, image_key: str, label_key: str, offset: int = 0
) -> Dict[str, np.ndarray]:
    def read_idx(path: str) -> np.ndarray:
        with gzip.open(path, "rb") as f:
            magic, = struct.unpack(">H", f.read(4)[2:])
            ndim = magic & 0xFF
            dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

    all_images = read_idx(os.path.join(root, "train-images-idx3-ubyte.gz"))
    all_labels = read_idx(os.path.join(root, "train-labels-idx1-ubyte.gz"))
    if offset + size > len(all_images):
        offset = max(0, len(all_images) - size)
    images = all_images[offset : offset + size]
    labels = all_labels[offset : offset + size]
    return {
        image_key: (images.astype(np.float32) / 255.0)[..., None],
        label_key: labels.astype(np.int32),
    }
