"""Deterministic index pipeline: shuffle -> shard -> batch -> skip.

Reference: ``harness/determined/pytorch/samplers.py`` (DistributedSampler,
SkipBatchSampler, ReproducibleShuffleSampler) and the ordering contract
documented there: **shuffle first, then shard, then batch, then skip** so a
resumed trial sees exactly the batches it would have seen uninterrupted.

TPU-first notes:
- batches are always full (drop_last): static shapes for XLA.
- sharding is by data-parallel *process* (each host feeds its addressable
  slice of the global batch; `jax.make_array_from_process_local_data`
  assembles the global array in the loader).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List

import numpy as np


@dataclasses.dataclass
class SamplerState:
    """Resume state: epoch + batches already consumed in that epoch."""

    epoch: int = 0
    batches_in_epoch: int = 0


class IndexSampler:
    """Yields per-epoch lists of global indices for THIS shard, batched."""

    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        *,
        shard_rank: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
        seed: int = 0,
    ) -> None:
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if batch_size % num_shards:
            raise ValueError(
                f"global batch size {batch_size} not divisible by {num_shards} shards"
            )
        if not (0 <= shard_rank < num_shards):
            raise ValueError(f"shard_rank {shard_rank} not in [0, {num_shards})")
        self.dataset_len = dataset_len
        self.global_batch = batch_size
        self.shard_batch = batch_size // num_shards
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        # full global batches per epoch (drop_last over the global stream)
        self.batches_per_epoch = dataset_len // batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"dataset of {dataset_len} records smaller than one global batch "
                f"({batch_size})"
            )

    def epoch_indices(self, epoch: int) -> np.ndarray:
        """Global index order for one epoch (same on every shard)."""
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch))
            return rng.permutation(self.dataset_len)
        return np.arange(self.dataset_len)

    def epoch_batches(self, epoch: int) -> np.ndarray:
        """[batches_per_epoch, shard_batch] index array for this shard.

        Shuffle -> batch -> shard: batch b covers global slice
        [b*B, (b+1)*B); this shard takes its contiguous sub-slice.
        """
        order = self.epoch_indices(epoch)
        usable = order[: self.batches_per_epoch * self.global_batch]
        batches = usable.reshape(self.batches_per_epoch, self.num_shards, self.shard_batch)
        return batches[:, self.shard_rank, :]

    def iter_from(self, state: SamplerState) -> Iterator[tuple]:
        """Infinite stream of (SamplerState, shard_indices) from a resume
        point; the state yielded is the position *after* the batch."""
        epoch, skip = state.epoch, state.batches_in_epoch
        while True:
            batches = self.epoch_batches(epoch)
            for b in range(skip, self.batches_per_epoch):
                yield SamplerState(epoch, b + 1), batches[b]
            epoch, skip = epoch + 1, 0
