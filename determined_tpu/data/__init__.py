"""Data pipeline: datasets, deterministic samplers, shard-aware loader,
overlapped prefetch stages (``docs/input-pipeline.md``)."""

from determined_tpu.data._dataset import (
    Dataset,
    InMemoryDataset,
    SyntheticDataset,
    mnist_like,
)
from determined_tpu.data._loader import (
    DataLoader,
    batch_spec,
    cached_batch_sharding,
    to_global,
)
from determined_tpu.data._prefetch import (
    EpochFeed,
    InputPipeline,
    PrefetchingIterator,
    device_prefetch,
)
from determined_tpu.data._sampler import IndexSampler, SamplerState

__all__ = [
    "Dataset",
    "EpochFeed",
    "InMemoryDataset",
    "InputPipeline",
    "PrefetchingIterator",
    "SyntheticDataset",
    "mnist_like",
    "DataLoader",
    "batch_spec",
    "cached_batch_sharding",
    "device_prefetch",
    "to_global",
    "IndexSampler",
    "SamplerState",
]
