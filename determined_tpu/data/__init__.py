"""Data pipeline: datasets, deterministic samplers, shard-aware loader."""

from determined_tpu.data._dataset import (
    Dataset,
    InMemoryDataset,
    SyntheticDataset,
    mnist_like,
)
from determined_tpu.data._loader import DataLoader, batch_spec, to_global
from determined_tpu.data._sampler import IndexSampler, SamplerState

__all__ = [
    "Dataset",
    "InMemoryDataset",
    "SyntheticDataset",
    "mnist_like",
    "DataLoader",
    "batch_spec",
    "to_global",
    "IndexSampler",
    "SamplerState",
]
