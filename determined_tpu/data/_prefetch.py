"""Overlapped input pipeline: background host prefetch + device double buffering.

The synchronous loop costs one full input latency per step: the device
waits while the host gathers/stacks the next batch, then the host waits
while the device computes.  This module decouples the three stages the way
tf.data / DALI do, adapted to JAX global arrays and the resumable sampler:

1. **host fetch** — a background worker thread runs the sampler + ``_fetch``
   (and, under gradient accumulation, the microbatch stacking) into a
   bounded queue (``PrefetchingIterator``);
2. **host→device** — ``to_global`` is called eagerly on batch N+1 while the
   device executes step N (``device_prefetch``); JAX transfers are
   asynchronous, so the copy rides under the compute;
3. **device compute** — the trainer's jitted step, unchanged.

Exact-resume invariant (the part PyTorch's DataLoader gets for free by
re-creating workers on restore): the sampler state checkpointed must be
that of the batch the *trainer consumed*, not the batch the worker
*fetched*.  Every stage therefore carries ``(SamplerState, batch)`` pairs,
and only ``InputPipeline.__next__`` — on the consumer thread, at the moment
the trainer takes the batch — commits the state back to the loader.  A
crash/restore then replays zero and skips zero batches no matter how far
ahead the worker ran.

Failure semantics: an exception on the worker (including one injected at
the ``data.prefetch.fetch`` fault site) is queued and re-raised from the
consumer's next ``__next__`` with its original type, so the supervised
restart path classifies it exactly like a synchronous input failure.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from determined_tpu.observability import get_tracer
from determined_tpu.utils import faults


class _WorkerError:
    """Envelope carrying a worker exception across the queue."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


_DONE = object()


class PrefetchingIterator:
    """Run any iterator on a background thread behind a bounded queue.

    ``depth`` bounds how far the worker may run ahead of the consumer
    (memory bound = depth batches + one in flight).  ``close()`` is
    idempotent, never blocks on a full queue, and joins the worker; an
    un-closed iterator's worker parks on the stop event and dies with the
    process (daemon thread).
    """

    def __init__(
        self,
        source: Iterable[Any],
        *,
        depth: int = 2,
        name: str = "dtpu-prefetch",
        fault_site: str = "data.prefetch.fetch",
    ) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = source
        self._fault_site = fault_site
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    # -- worker side ---------------------------------------------------------

    def _put(self, item: Any) -> bool:
        """Blocking put that wakes up if the consumer closes us."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        produced = 0
        tracer = get_tracer()
        try:
            it = iter(self._source)
            while not self._stop.is_set():
                # fault-injection hook: tests kill the worker mid-stream here
                # to exercise exception propagation + supervised restart
                faults.fire(self._fault_site, batches=produced)
                try:
                    # the fetch span lives on THIS worker thread's trace
                    # track; the consumer's stall (if any) shows up as the
                    # trainer's data.wait span instead
                    t0 = time.monotonic()
                    item = next(it)
                    tracer.record_span("data.fetch", "data", t0, time.monotonic())
                except StopIteration:
                    self._put(_DONE)
                    return
                produced += 1
                if not self._put(item):
                    return
                # depth after the put: how far ahead of the consumer the
                # worker is running (0 sustained = input-bound training)
                tracer.gauge("data.queue_depth", float(self._queue.qsize()))
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer
            self._put(_WorkerError(e))

    # -- consumer side -------------------------------------------------------

    def __iter__(self) -> "PrefetchingIterator":
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        while True:
            try:
                item = self._queue.get(timeout=0.5)
                break
            except queue.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # worker died without queueing a sentinel (should be
                    # impossible; defensive against a hard thread kill)
                    self._done = True
                    raise RuntimeError("prefetch worker died without a result")
        if item is _DONE:
            self._done = True
            raise StopIteration
        if isinstance(item, _WorkerError):
            self._done = True
            raise item.exc
        return item

    def close(self) -> None:
        """Stop the worker and join it.  Safe to call more than once, from
        any state (mid-stream, exhausted, after an error)."""
        self._done = True
        self._stop.set()
        # drain so a worker blocked on put() sees the stop event promptly
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "PrefetchingIterator":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:  # belt-and-braces: never leak a live worker
        stop = getattr(self, "_stop", None)  # absent if __init__ raised
        if stop is not None:
            stop.set()


def device_prefetch(
    pairs: Iterable[Tuple[Any, Dict[str, np.ndarray]]],
    mesh: Any,
    *,
    size: int = 2,
    micro_dim: bool = False,
) -> Iterator[Tuple[Any, Dict[str, Any]]]:
    """Eager ``to_global`` stage: keep ``size`` device batches in flight.

    Yields ``(state, global_batch)`` pairs.  With ``size`` >= 2 the
    host→device transfer of batch N+1 is dispatched before batch N is
    consumed, so it overlaps the device step (JAX transfers are async).
    ``size`` <= 1 degrades to synchronous conversion.
    """
    from determined_tpu.data._loader import to_global

    tracer = get_tracer()

    def _to_global_traced(host_batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        # transfer *dispatch* time (JAX copies asynchronously): runs on the
        # consumer thread, so it nests inside the trainer's data.wait span
        t0 = time.monotonic()
        out = to_global(host_batch, mesh, micro_dim=micro_dim)
        tracer.record_span("data.h2d", "h2d", t0, time.monotonic())
        return out

    if size <= 1:
        for state, host_batch in pairs:
            yield state, _to_global_traced(host_batch)
        return
    buf: collections.deque = collections.deque()
    for state, host_batch in pairs:
        buf.append((state, _to_global_traced(host_batch)))
        if len(buf) >= size:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


class EpochFeed:
    """Overlapped feed over one finite pass (a validation sweep): the same
    host-prefetch + device-prefetch stages as ``InputPipeline``, minus the
    resume-state commit (``iter_epoch`` never touches sampler state)."""

    def __init__(
        self,
        host_iter: Iterable[Dict[str, np.ndarray]],
        mesh: Any,
        *,
        prefetch_depth: int = 2,
        device_buffer: int = 2,
        micro_dim: bool = False,
    ) -> None:
        self._host_stage: Optional[PrefetchingIterator] = None
        if prefetch_depth > 0:
            host_iter = self._host_stage = PrefetchingIterator(
                host_iter, depth=prefetch_depth, name="dtpu-prefetch-epoch"
            )
        self._it = device_prefetch(
            ((None, hb) for hb in host_iter),
            mesh,
            size=device_buffer,
            micro_dim=micro_dim,
        )

    def __iter__(self) -> "EpochFeed":
        return self

    def __next__(self) -> Dict[str, Any]:
        return next(self._it)[1]

    def close(self) -> None:
        if self._host_stage is not None:
            self._host_stage.close()

    def __enter__(self) -> "EpochFeed":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class InputPipeline:
    """The full three-stage feed bound to one resumable DataLoader.

    ``__next__`` returns a device-global batch (stacked ``[agg, batch, ...]``
    microbatches when ``agg`` > 1) and commits the loader's resume state to
    the position *after* the consumed batch — ``loader.state_dict()`` at any
    point between two ``__next__`` calls is an exact resume point.
    """

    def __init__(
        self,
        loader: Any,
        mesh: Any,
        *,
        agg: int = 1,
        prefetch_depth: int = 2,
        device_buffer: int = 2,
    ) -> None:
        self.loader = loader
        self._host_stage: Optional[PrefetchingIterator] = None
        source: Iterable[Tuple[Any, Dict[str, np.ndarray]]] = loader.iter_pairs(agg=agg)
        if prefetch_depth > 0:
            source = self._host_stage = PrefetchingIterator(source, depth=prefetch_depth)
        self._it = device_prefetch(
            source, mesh, size=device_buffer, micro_dim=agg > 1
        )

    def __iter__(self) -> "InputPipeline":
        return self

    def __next__(self) -> Dict[str, Any]:
        state, batch = next(self._it)
        self.loader.commit_state(state)
        return batch

    def close(self) -> None:
        if self._host_stage is not None:
            self._host_stage.close()

    def __enter__(self) -> "InputPipeline":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
