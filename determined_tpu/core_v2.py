"""core_v2: unmanaged experiments — tracked by the master, run by you.

Reference: ``harness/determined/experimental/core_v2/_core_v2.py:27-124`` +
``_unmanaged.py``: a wandb-style mode where any Python process registers an
experiment+trial with the master, reports metrics/checkpoints through the
normal Core API, and the master never schedules anything.  Usage::

    from determined_tpu import core_v2

    with core_v2.init(config={"name": "my-run"}, master="http://master:8080") as run:
        for step in range(100):
            ...
            run.train.report_training_metrics(step, {"loss": loss})

On exit the trial completes (ERROR if the block raised); the run shows up
in `dtpu experiment list`, the WebUI-equivalent APIs, and the SDK like any
managed experiment.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from determined_tpu import core
from determined_tpu.api.authentication import ensure_session
from determined_tpu.core._cluster_info import ClusterInfo


class UnmanagedRun:
    """Context-manager wrapper: delegates to the Core API Context and
    reports the trial exit to the master on close."""

    def __init__(self, ctx: core.Context, session, trial_id: int, experiment_id: int):
        self.core = ctx
        self._session = session
        self.trial_id = trial_id
        self.experiment_id = experiment_id
        self._closed = False

    def __getattr__(self, name: str) -> Any:
        return getattr(self.core, name)

    def close(self, exit_code: int = 0) -> None:
        if self._closed:
            return
        self._closed = True
        self.core.close()
        try:
            self._session.post(
                f"/api/v1/trials/{self.trial_id}/exit", json={"exit_code": exit_code}
            )
        except Exception:  # noqa: BLE001 - master may be gone; run is local
            pass

    def __enter__(self) -> "UnmanagedRun":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(exit_code=0 if exc_type is None else 1)


def init(
    *,
    config: Optional[Dict[str, Any]] = None,
    master: Optional[str] = None,
    user: Optional[str] = None,
    password: Optional[str] = None,
    checkpoint_storage: Optional[str] = None,
) -> UnmanagedRun:
    """Register an unmanaged experiment and return a live run handle.

    Falls back to a fully-local dummy context when no master is reachable
    (same contract as ``core.init`` off-cluster).
    """
    master = master or os.environ.get("DTPU_MASTER") or os.environ.get(
        "DTPU_MASTER_URL"
    )
    cfg = dict(config or {})
    cfg.setdefault("name", "unmanaged")
    cfg["unmanaged"] = True
    cfg.setdefault(
        "searcher",
        {"name": "single", "metric": "loss", "max_length": {"batches": 1}},
    )
    if checkpoint_storage:
        cfg.setdefault(
            "checkpoint_storage",
            {"type": "shared_fs", "host_path": checkpoint_storage},
        )

    if not master:
        ctx = core._dummy_init(checkpoint_dir=checkpoint_storage)
        return UnmanagedRun(ctx, session=None, trial_id=0, experiment_id=0)

    session = ensure_session(master, user, password)
    exp = session.post("/api/v1/experiments", json={"config": cfg}).json()
    exp_id = int(exp["id"])
    detail = session.get(f"/api/v1/experiments/{exp_id}").json()
    trial_id = int(detail["trials"][0]["id"])

    info = ClusterInfo(
        master_url=master,
        session_token=session.token or "",
        trial_id=trial_id,
        experiment_id=exp_id,
        hparams=cfg.get("hyperparameters") or {},
        exp_config=cfg,
    )
    ctx = core.init(info=info, checkpoint_storage=checkpoint_storage)
    # first heartbeat flips the unmanaged trial RUNNING
    try:
        session.post(f"/api/v1/trials/{trial_id}/heartbeat")
    except Exception:  # noqa: BLE001
        pass
    return UnmanagedRun(ctx, session, trial_id, exp_id)
