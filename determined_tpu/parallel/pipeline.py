"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pipe``
mesh axis.

The reference's deepest pipeline support is a DeepSpeed passthrough
(``deepspeed/_mpu.py`` — topology bookkeeping, engine owned by DeepSpeed);
this is the TPU-native schedule itself.  Design (the SPMD pipelining
pattern from the scaling playbook): stage parameters are STACKED on a
leading ``[P, ...]`` dim sharded over ``pipe``; the whole schedule is one
``lax.scan`` inside ``shard_map``, where every tick each device applies
ITS stage to its current activation and hands the result to the next stage
with a single ``ppermute`` rotation.  M microbatches drain in M + P - 1
ticks (the GPipe bubble); reverse-mode AD differentiates straight through
the scan + ppermute (its transpose is the reverse rotation), so the same
function trains.

Composition: the batch dim may simultaneously be sharded over data/fsdp
axes — specs below only partition ``pipe``; other mesh axes pass through
untouched (activations replicate across them exactly as in the non-pipelined
model).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_tpu.parallel.mesh import MeshAxes


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh,
    num_microbatches: int,
) -> jax.Array:
    """Run ``stage_fn`` across the mesh's ``pipe`` stages.

    - ``stacked_params``: pytree whose leaves have leading dim P (one slice
      per stage), placed with the leading dim sharded over ``pipe``;
    - ``x``: ``[batch, ...]`` global input; batch must divide into
      ``num_microbatches``;
    - returns ``[batch, ...]`` outputs, as if the stages were applied
      sequentially to each microbatch.
    """
    n_stages = mesh.shape.get(MeshAxes.PIPELINE, 1)
    if n_stages == 1:
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return stage_fn(params0, x)

    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {num_microbatches} microbatches"
        )
    mb = batch // num_microbatches
    xm = x.reshape(num_microbatches, mb, *x.shape[1:])
    bshards = 1
    for a in (MeshAxes.DATA, MeshAxes.FSDP):
        bshards *= mesh.shape.get(a, 1)

    try:  # jax >= 0.6 moved shard_map to jax.shard_map
        shard_map = jax.shard_map
    except AttributeError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map  # type: ignore

    pspec = jax.tree.map(lambda _: P(MeshAxes.PIPELINE), stacked_params)
    # microbatch rows shard over the batch axes present in the mesh, so
    # data/fsdp parallelism composes through the pipeline instead of being
    # silently all-gathered away by a replicated in_spec; microbatches too
    # small to split fall back to replication (still correct, no speedup)
    batch_axes = tuple(
        a for a in (MeshAxes.DATA, MeshAxes.FSDP) if mesh.shape.get(a, 1) > 1
    )
    if mb % bshards:
        batch_axes = ()
    xspec = P(None, batch_axes or None, *([None] * (x.ndim - 1)))

    def per_device(params, xm_local):
        # params leaves: [1, ...] (my stage); xm_local: [M, mb, ...]
        my = jax.tree.map(lambda a: a[0], params)
        p = jax.lax.axis_index(MeshAxes.PIPELINE)
        n = n_stages
        m = xm_local.shape[0]
        ticks = m + n - 1

        zero = jnp.zeros_like(xm_local[0])
        outputs = jnp.zeros_like(xm_local)

        def tick(carry, t):
            state_in, outs = carry
            # stage 0 ingests microbatch t while it exists; later stages
            # consume the rotated activation from the previous tick
            fresh = jax.lax.dynamic_index_in_dim(
                xm_local, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            use_fresh = jnp.logical_and(p == 0, t < m)
            x_in = jnp.where(use_fresh, fresh, state_in)
            y = stage_fn(my, x_in)
            # last stage emits microbatch t - (n - 1)
            out_idx = t - (n - 1)
            prev = jax.lax.dynamic_index_in_dim(
                outs, jnp.clip(out_idx, 0, m - 1), 0, keepdims=False
            )
            valid = jnp.logical_and(
                p == n - 1, jnp.logical_and(out_idx >= 0, out_idx < m)
            )
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, prev), jnp.clip(out_idx, 0, m - 1), 0
            )
            # rotate activations one stage forward
            state_out = jax.lax.ppermute(
                y, MeshAxes.PIPELINE, [(i, (i + 1) % n) for i in range(n)]
            )
            return (state_out, outs), None

        (_, outputs), _ = jax.lax.scan(tick, (zero, outputs), jnp.arange(ticks))
        # outputs accumulated on the last stage only (zeros elsewhere):
        # psum replicates the final result across the pipe axis
        return jax.lax.psum(outputs, MeshAxes.PIPELINE)

    out = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(stacked_params, xm)
    return out.reshape(batch, *x.shape[1:])


def stack_stage_params(param_list) -> Any:
    """Stack per-stage parameter pytrees into the leading-``P`` layout
    ``pipeline_apply`` consumes."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_list)
