"""Pipeline parallelism: schedule-driven microbatch pipelining over the
``pipe`` mesh axis.

The reference's deepest pipeline support is a DeepSpeed passthrough
(``deepspeed/_mpu.py`` — topology bookkeeping, engine owned by DeepSpeed);
this is the TPU-native schedule itself.  Design (the SPMD pipelining
pattern from the scaling playbook): stage parameters are STACKED on a
leading ``[P, ...]`` dim sharded over ``pipe``; the whole schedule is one
``lax.scan`` inside ``shard_map``, where every tick each device applies
ITS stage to its current activation and hands the result to the next stage
with a single ``ppermute`` rotation.

Three schedules (``optimizations.pipeline_schedule``), all a single jitted
SPMD program with static trip counts — one trace, RetraceSentinel-clean:

- ``gpipe``: M microbatches drain in M + P - 1 ticks; reverse-mode AD
  differentiates straight through the scan + ppermute (its transpose is
  the reverse rotation).  Every tick's stage residuals are saved for
  backward, so live activations grow with M.
- ``1f1b``: same forward numerics and tick count, but the backward is a
  hand-written ``custom_vjp`` running ONE combined scan of
  2M + 2(P - 1) unit ticks that interleaves recomputed forward units with
  backward units (warmup of P - p forwards on stage p, then strict
  1F1B alternation).  Only a ring buffer of **P** stage-input
  activations is live at any tick — the Megatron-LM 1F1B memory cap,
  which is what buys larger M (hence a smaller bubble) at fixed HBM.
  Grad accumulation per stage runs in increasing-microbatch order (the
  scan-transpose of gpipe accumulates decreasing), so params agree with
  gpipe up to float reassociation; the loss itself is bit-exact.
- ``interleaved``: circular-interleaved virtual stages (GSPMD-style
  circular pipelining; Megatron's interleaved schedule).  Each pipe rank
  holds V NON-adjacent layer chunks — rank p owns chunks {v*P + p} on a
  ``[P, V, ...]`` param layout — and the existing ``(i+1) % P`` rotation
  IS the circular wrap: chunk c ends on rank P-1 and chunk c+1 starts on
  rank 0 one tick later.  Microbatches feed in groups of P, so the drain
  takes V*M + P - 1 ticks and the bubble fraction falls from
  (P-1)/(M+P-1) toward (P-1)/(V*M + P-1).

``PipelineSchedule`` is the analytic tick model behind all three (total /
busy / bubble ticks); ``BubbleModel`` folds it into the goodput ledger's
``step.bubble`` rows the way ``train/_overlap.py``'s CommModel feeds
``step.comm``.

Composition — the pipe axis composes with every other mesh axis (the
"one mesh subsumes the zoo" design claim, SURVEY §7):
- **data/fsdp**: microbatch rows stay sharded over the batch axes inside
  the schedule (specs below partition both pipe and batch);
- **seq**: the sequence dim of activations stays sharded over the seq
  axis; ring attention runs INSIDE each stage's blocks (the ring is over
  seq shards, orthogonal to the stage rotation over pipe) — see
  ``models/transformer.py`` ``seq_axis_name``;
- **expert**: MoE expert weights are sharded over the expert axis WITHIN
  each stage (``expert_leaf_paths``), and the expert combine is a psum
  over the expert axis inside the stage — the all-to-all never crosses a
  stage boundary.  The reference's DeepSpeed grid composes PP only with
  DP/TP (``deepspeed/_mpu.py:9-50``); seq and expert composition is net-new.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_tpu.config.experiment import (
    PIPELINE_SCHEDULES as SCHEDULES,
    InvalidExperimentConfig,
)
from determined_tpu.parallel.mesh import MeshAxes

# MoE expert-weight param names: leading dim (after the stage stack) is the
# expert dim, shardable over the expert mesh axis.
_EXPERT_PARAM_NAMES = frozenset({"w_in", "w_gate", "w_out"})


def _path_has_expert_leaf(path) -> bool:
    keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
    return any(k == "moe" for k in keys) and keys[-1] in _EXPERT_PARAM_NAMES


# ---------------------------------------------------------------------------
# Analytic tick model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PipelineSchedule:
    """Static description of one pipeline schedule: the analytic tick
    model behind both the runtime dispatch and the goodput ledger's
    bubble accounting.  Validation raises ``InvalidExperimentConfig`` so
    a bad knob fails at config/setup time, not at first step."""

    name: str = "gpipe"
    n_stages: int = 1
    num_microbatches: int = 1
    virtual_stages: int = 1

    def __post_init__(self) -> None:
        if self.name not in SCHEDULES:
            raise InvalidExperimentConfig(
                f"pipeline_schedule {self.name!r} not in {SCHEDULES}"
            )
        if self.n_stages < 1 or self.num_microbatches < 1:
            raise InvalidExperimentConfig(
                f"pipeline schedule needs n_stages >= 1 and microbatches >= 1 "
                f"(got P={self.n_stages}, M={self.num_microbatches})"
            )
        if self.virtual_stages < 1:
            raise InvalidExperimentConfig(
                f"virtual_stages must be >= 1 (got {self.virtual_stages})"
            )
        if self.name == "interleaved" and self.virtual_stages < 2:
            raise InvalidExperimentConfig(
                "pipeline_schedule: interleaved needs virtual_stages >= 2 "
                f"(got {self.virtual_stages}); with one virtual stage it IS "
                "gpipe — set pipeline_schedule: gpipe instead"
            )
        if self.name != "interleaved" and self.virtual_stages != 1:
            raise InvalidExperimentConfig(
                f"virtual_stages={self.virtual_stages} only applies to "
                f"pipeline_schedule: interleaved (got {self.name!r})"
            )

    @property
    def total_ticks(self) -> int:
        """Schedule makespan in unit ticks (one stage/chunk application —
        for 1f1b, one forward OR backward unit)."""
        p, m, v = self.n_stages, self.num_microbatches, self.virtual_stages
        if p <= 1:
            return m * v
        if self.name == "interleaved":
            # microbatch m-1 = group q, offset r; its last chunk (V*P-1)
            # runs on rank P-1 at tick q*V*P + (V-1)*P + r + (P-1)
            q, r = divmod(m - 1, p)
            return q * v * p + (v - 1) * p + r + p
        if self.name == "1f1b":
            return 2 * (m + p - 1)
        return m + p - 1  # gpipe forward drain

    @property
    def work_ticks(self) -> int:
        """Busy ticks per device (each device does every microbatch)."""
        p, m, v = self.n_stages, self.num_microbatches, self.virtual_stages
        if p <= 1:
            return self.total_ticks
        if self.name == "interleaved":
            return v * m
        if self.name == "1f1b":
            return 2 * m  # one F and one B unit per microbatch
        return m

    @property
    def bubble_ticks(self) -> int:
        return self.total_ticks - self.work_ticks

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of the schedule: (P-1)/(M+P-1) for gpipe AND
        1f1b (1f1b trades memory, not bubble), (P-1)/(V*M+P-1) for
        interleaved when P | M."""
        return self.bubble_ticks / max(self.total_ticks, 1)

    @property
    def live_activation_microbatches(self) -> int:
        """How many microbatches of stage-input activations the schedule
        keeps live for backward: the 1f1b stash is a ring of P; the AD
        schedules save one residual set per scan tick."""
        if self.n_stages <= 1:
            return 1
        if self.name == "1f1b":
            return min(self.n_stages, self.num_microbatches)
        return self.total_ticks

    def fingerprint(self) -> str:
        """jit-reuse cache key material: every field shapes the traced
        program (trip counts, param layout, custom backward)."""
        return (
            f"pipe:{self.name}:p={self.n_stages}"
            f":m={self.num_microbatches}:v={self.virtual_stages}"
        )


@dataclasses.dataclass(frozen=True)
class BubbleModel:
    """Analytic exposed-bubble model for the ``step.bubble`` ledger rows —
    the pipeline analog of ``train/_overlap.py``'s CommModel.  The split
    applies the schedule's idle fraction to the measured step time; it is
    a *model* (labeled ``pipeline-tick-v1`` in the ledger) that treats the
    whole step as pipeline ticks — embed/head/optimizer time outside the
    schedule makes it an upper bound.  The xplane op table stays the
    ground truth on real chips."""

    schedule: PipelineSchedule

    MODEL = "pipeline-tick-v1"

    @property
    def fraction(self) -> float:
        return self.schedule.bubble_fraction

    def split(self, avg_step_s: float) -> Tuple[float, float]:
        """(bubble_s, busy_s) per step under the tick model."""
        step = max(avg_step_s, 0.0)
        bubble = step * self.fraction
        return bubble, step - bubble


# ---------------------------------------------------------------------------
# Per-device schedule loops (inside shard_map)
# ---------------------------------------------------------------------------


def _gpipe_ticks(fn, my, xm_local, n: int):
    """The GPipe forward drain: M + P - 1 ticks, one rotation per tick.
    Differentiable by construction (gpipe AD path) and reused as the
    primal/fwd of the 1f1b custom_vjp — both schedules share these exact
    forward numerics."""
    p = jax.lax.axis_index(MeshAxes.PIPELINE)
    m = xm_local.shape[0]
    ticks = m + n - 1

    zero = jnp.zeros_like(xm_local[0])
    outputs = jnp.zeros_like(xm_local)
    aux0 = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        state_in, outs, aux_sum = carry
        # stage 0 ingests microbatch t while it exists; later stages
        # consume the rotated activation from the previous tick
        fresh = jax.lax.dynamic_index_in_dim(
            xm_local, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        use_fresh = jnp.logical_and(p == 0, t < m)
        x_in = jnp.where(use_fresh, fresh, state_in)
        y, aux = fn(my, x_in)
        # stage p processes microbatch t - p at tick t; outside [0, m)
        # the input is warm-up/drain garbage — gate its aux out
        mb_idx = t - p
        work_valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
        aux_sum = aux_sum + jnp.where(work_valid, aux, 0.0)
        # last stage emits microbatch t - (n - 1)
        out_idx = t - (n - 1)
        prev = jax.lax.dynamic_index_in_dim(
            outs, jnp.clip(out_idx, 0, m - 1), 0, keepdims=False
        )
        valid = jnp.logical_and(
            p == n - 1, jnp.logical_and(out_idx >= 0, out_idx < m)
        )
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, y, prev), jnp.clip(out_idx, 0, m - 1), 0
        )
        # rotate activations one stage forward
        state_out = jax.lax.ppermute(
            y, MeshAxes.PIPELINE, [(i, (i + 1) % n) for i in range(n)]
        )
        return (state_out, outs, aux_sum), None

    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (zero, outputs, aux0), jnp.arange(ticks)
    )
    return outputs, aux_sum


def _make_1f1b(fn, n: int):
    """1F1B as a ``custom_vjp`` over (stage params, microbatched input).

    Forward: the gpipe drain verbatim (bit-exact loss), saving ONLY
    (params, input) — no per-tick residuals.  Backward: one scan of
    2M + 2(P-1) unit ticks; each tick a device is (at most) one of

    - an **F unit** — recompute forward of microbatch f, stash its stage
      input in a ring buffer of P slots (slot f mod P), rotate the output
      one stage forward;
    - a **B unit** — vjp through this stage for microbatch b, consuming
      the stashed input and the cotangent rotated back from stage p+1
      (the last stage reads the output cotangent directly), rotate the
      input cotangent one stage back.

    The tick grid (stage p, microbatch k): F units at p + k during warmup
    (k < P - p) then p + 2k; B units at 2P - 1 - p + 2k.  F parity is
    p + k mod 2 in warmup / p mod 2 in steady state, B parity is p + 1 —
    never both in one tick, so one fn evaluation per tick serves both
    roles (the vjp's primal IS the forward recompute).  The stash slot
    for f + P is rewritten strictly after the B unit of f reads it
    (t_B(p, f) = 2P-1-p+2f < p + 2(f+P) = t_F(p, f+P)), so P slots
    suffice — the live-activation cap the schedule exists for.

    Activation arrival: the rotating register is a ONE-tick buffer, and
    on this grid every F unit consumes the value rotated in that same
    tick — with exactly one exception per stage.  Microbatch f* = P - p
    is stage p-1's last warmup forward (tick P-1, so it arrives at tick
    P) but stage p's FIRST steady forward (tick 2P - p): the single
    microbatch that crosses the warmup/steady boundary.  A one-register
    ``held`` parks that arrival until its F unit runs; everything else
    is same-tick (warmup: both stages on the p + k diagonal; steady
    f > f*: both stages on p + 2k).  This is the SPMD analog of the recv
    queue a message-passing 1F1B keeps per stage — depth 1 here because
    only one microbatch per stage transitions between regimes.
    """

    def primal(my, xm_local):
        return _gpipe_ticks(fn, my, xm_local, n)

    run = jax.custom_vjp(primal)

    def fwd(my, xm_local):
        return primal(my, xm_local), (my, xm_local)

    def bwd(res, cts):
        my, xm_local = res
        d_out, d_aux = cts
        p = jax.lax.axis_index(MeshAxes.PIPELINE)
        m = xm_local.shape[0]
        ticks = 2 * m + 2 * (n - 1)

        act0 = jnp.zeros_like(xm_local[0])
        stash0 = jnp.zeros((n,) + xm_local.shape[1:], xm_local.dtype)
        dmy0 = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), my)
        dxm0 = jnp.zeros_like(xm_local)

        def tick(carry, t):
            fwd_in, bwd_in, held, stash, dmy, dxm = carry
            u = t - p
            # F unit: warmup t in [p, P-1] (f = u), steady t = p + 2f
            warm = jnp.logical_and(u >= 0, t <= n - 1)
            steady = jnp.logical_and(u >= 2 * (n - p), u % 2 == 0)
            f = jnp.where(warm, u, u // 2)
            f_active = jnp.logical_and(jnp.logical_or(warm, steady), f < m)
            f_idx = jnp.clip(f, 0, m - 1)
            # B unit: t = 2P - 1 - p + 2b
            w = t - (2 * n - 1 - p)
            b = w // 2
            b_active = jnp.logical_and(
                jnp.logical_and(w >= 0, w % 2 == 0), b < m
            )
            b_idx = jnp.clip(b, 0, m - 1)

            # the one cross-regime microbatch f* = P - p arrives at tick
            # P (stage p-1's warmup tail) but runs at tick 2P - p: park
            # it in `held` on arrival, consume it at its F unit
            hold_f = n - p
            park = jnp.logical_and(p > 0, t == n)
            held = jnp.where(park, fwd_in, held)
            use_held = jnp.logical_and(steady, f == hold_f)

            fresh = jax.lax.dynamic_index_in_dim(
                xm_local, f_idx, 0, keepdims=False
            )
            x_f = jnp.where(
                p == 0, fresh, jnp.where(use_held, held, fwd_in)
            )
            x_b = jax.lax.dynamic_index_in_dim(
                stash, b_idx % n, 0, keepdims=False
            )
            # F and B are never co-active (parity), so one vjp serves
            # both: its primal output is the F result, its pullback the
            # B result — zero cotangents make the unused pullback inert
            x_sel = jnp.where(b_active, x_b, x_f)
            (y, aux), pull = jax.vjp(fn, my, x_sel)
            ct_from_next = jnp.where(
                p == n - 1,
                jax.lax.dynamic_index_in_dim(d_out, b_idx, 0, keepdims=False),
                bwd_in,
            )
            ct_y = jnp.where(b_active, ct_from_next, jnp.zeros_like(y))
            ct_aux = jnp.where(b_active, d_aux, jnp.zeros_like(aux))
            dmy_t, dx_t = pull((ct_y, ct_aux))
            dmy = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_active, g, jnp.zeros_like(g)),
                dmy,
                dmy_t,
            )
            # stage 0's input cotangent IS the xm cotangent (other stages
            # rotate theirs back; their dxm rows stay zero and the
            # shard_map transpose sums them away, as in the gpipe path)
            cur = jax.lax.dynamic_index_in_dim(dxm, b_idx, 0, keepdims=False)
            write0 = jnp.logical_and(b_active, p == 0)
            dxm = jax.lax.dynamic_update_index_in_dim(
                dxm, jnp.where(write0, dx_t, cur), b_idx, 0
            )
            # stash write AFTER the B read: slot f mod P
            scur = jax.lax.dynamic_index_in_dim(
                stash, f_idx % n, 0, keepdims=False
            )
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_active, x_f, scur), f_idx % n, 0
            )
            # both streams rotate every tick; garbage self-gates at the
            # consumer (F consumption implies the producer was F-active
            # one tick earlier — see the tick-grid proof above)
            fwd_out = jax.lax.ppermute(
                y, MeshAxes.PIPELINE, [(i, (i + 1) % n) for i in range(n)]
            )
            bwd_out = jax.lax.ppermute(
                dx_t, MeshAxes.PIPELINE, [(i, (i - 1) % n) for i in range(n)]
            )
            return (fwd_out, bwd_out, held, stash, dmy, dxm), None

        (_, _, _, _, dmy, dxm), _ = jax.lax.scan(
            tick, (act0, act0, act0, stash0, dmy0, dxm0), jnp.arange(ticks)
        )
        return dmy, dxm

    run.defvjp(fwd, bwd)
    return run


def _interleaved_ticks(fn, my, xm_local, n: int, v_stages: int):
    """Circular-interleaved drain: each device holds V chunks (leading
    ``[V, ...]`` dim after the stage slice) and applies chunk v of
    microbatch m = q*P + r at tick p + q*V*P + v*P + r.  The single
    ``(i+1) % P`` rotation carries both intra-chunk handoffs and the
    circular wrap (chunk c ends on rank P-1, chunk c+1 starts on rank 0
    one tick later).  Differentiated by AD like gpipe — interleaving
    buys bubble, not memory."""
    p = jax.lax.axis_index(MeshAxes.PIPELINE)
    m = xm_local.shape[0]
    sched = PipelineSchedule(
        name="interleaved",
        n_stages=n,
        num_microbatches=m,
        virtual_stages=v_stages,
    )
    ticks = sched.total_ticks
    vp = v_stages * n

    zero = jnp.zeros_like(xm_local[0])
    outputs = jnp.zeros_like(xm_local)
    aux0 = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        state_in, outs, aux_sum = carry
        u = t - p
        # u = q*V*P + v*P + r  (floor/mod keep remainders in range for
        # u < 0; activity gates on u >= 0 and the microbatch bound)
        q = u // vp
        rem = u % vp
        v = rem // n
        r = rem % n
        mb = q * n + r
        active = jnp.logical_and(u >= 0, jnp.logical_and(mb >= 0, mb < m))
        mb_idx = jnp.clip(mb, 0, m - 1)
        my_v = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(v, 0, v_stages - 1), 0, keepdims=False
            ),
            my,
        )
        fresh = jax.lax.dynamic_index_in_dim(
            xm_local, mb_idx, 0, keepdims=False
        )
        # chunk 0 (rank 0, virtual stage 0) ingests a fresh microbatch;
        # everything else continues the rotated activation
        use_fresh = jnp.logical_and(p == 0, jnp.logical_and(v == 0, active))
        x_in = jnp.where(use_fresh, fresh, state_in)
        y, aux = fn(my_v, x_in)
        aux_sum = aux_sum + jnp.where(active, aux, 0.0)
        # the LAST chunk (rank P-1, virtual stage V-1) emits the output
        emit = jnp.logical_and(
            p == n - 1, jnp.logical_and(v == v_stages - 1, active)
        )
        prev = jax.lax.dynamic_index_in_dim(outs, mb_idx, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(emit, y, prev), mb_idx, 0
        )
        state_out = jax.lax.ppermute(
            y, MeshAxes.PIPELINE, [(i, (i + 1) % n) for i in range(n)]
        )
        return (state_out, outs, aux_sum), None

    (_, outputs, aux_sum), _ = jax.lax.scan(
        tick, (zero, outputs, aux0), jnp.arange(ticks)
    )
    return outputs, aux_sum


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], Any],
    stacked_params: Any,
    x: jax.Array,
    mesh,
    num_microbatches: int,
    with_aux: bool = False,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> Any:
    """Run ``stage_fn`` across the mesh's ``pipe`` stages.

    - ``stacked_params``: pytree whose leaves have leading dim P (one slice
      per stage), placed with the leading dim sharded over ``pipe``; for
      ``schedule="interleaved"`` the leaves lead with ``[P, V, ...]``
      (stage-major, virtual-stage minor — ``[p, v]`` is chunk ``v*P + p``);
      MoE expert-weight leaves (``.../moe/w_*``) are additionally sharded
      over the expert axis on their first post-stack dim;
    - ``x``: ``[batch, ...]`` global input; batch must divide into
      ``num_microbatches``; when the mesh has a seq axis, dim 1 of ``x``
      is the (sharded) sequence dim;
    - ``with_aux``: ``stage_fn`` returns ``(y, aux_scalar)``; the schedule
      accumulates aux over VALID ticks only (warm-up/drain garbage is
      gated out) and returns ``(out, aux)`` with aux averaged over
      microbatches and summed over stages — matching the unpipelined
      per-layer aux sum;
    - ``schedule``/``virtual_stages``: one of ``SCHEDULES`` (validated via
      ``PipelineSchedule``);
    - returns ``[batch, ...]`` outputs (plus aux), as if the stages were
      applied sequentially to each microbatch.
    """
    n_stages = mesh.shape.get(MeshAxes.PIPELINE, 1)
    if n_stages == 1:
        if schedule == "interleaved":
            raise InvalidExperimentConfig(
                "pipeline_schedule: interleaved needs a pipe mesh axis > 1 "
                f"(mesh has {dict(mesh.shape)})"
            )
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return stage_fn(params0, x)

    batch = x.shape[0]
    # validates schedule/virtual_stages/microbatches with clear errors
    sched = PipelineSchedule(
        name=schedule,
        n_stages=n_stages,
        num_microbatches=num_microbatches,
        virtual_stages=virtual_stages,
    )
    if batch % num_microbatches:
        raise InvalidExperimentConfig(
            f"global batch {batch} not divisible by pipe_microbatches "
            f"{num_microbatches} (pipeline_schedule {schedule!r}, "
            f"P={n_stages}): pick a microbatch count dividing the batch"
        )
    mb = batch // num_microbatches
    xm = x.reshape(num_microbatches, mb, *x.shape[1:])
    bshards = 1
    for a in MeshAxes.BATCH_AXES:
        bshards *= mesh.shape.get(a, 1)

    from determined_tpu.parallel._compat import shard_map

    expert_ax = (
        MeshAxes.EXPERT if mesh.shape.get(MeshAxes.EXPERT, 1) > 1 else None
    )
    interleaved = schedule == "interleaved"

    def leaf_spec(path, leaf):
        if expert_ax is not None and _path_has_expert_leaf(path):
            # expert dim sits after the stage (and virtual-stage) dims
            if interleaved:
                return P(MeshAxes.PIPELINE, None, expert_ax)
            return P(MeshAxes.PIPELINE, expert_ax)
        return P(MeshAxes.PIPELINE)

    pspec = jax.tree_util.tree_map_with_path(leaf_spec, stacked_params)
    # microbatch rows shard over the batch axes present in the mesh, so
    # data/fsdp parallelism composes through the pipeline instead of being
    # silently all-gathered away by a replicated in_spec; microbatches too
    # small to split fall back to replication (still correct, no speedup)
    batch_axes = tuple(
        a for a in MeshAxes.BATCH_AXES if mesh.shape.get(a, 1) > 1
    )
    if mb % bshards:
        batch_axes = ()
    # seq axis: dim 1 of the original x (dim 2 of xm) stays sharded — ring
    # attention inside the stage works on the local shard
    seq_ax = (
        MeshAxes.SEQUENCE
        if (x.ndim >= 2 and mesh.shape.get(MeshAxes.SEQUENCE, 1) > 1)
        else None
    )
    xspec = P(None, batch_axes or None, seq_ax, *([None] * (x.ndim - 2)))

    fn = stage_fn if with_aux else (lambda p, h: (stage_fn(p, h), jnp.zeros((), jnp.float32)))

    def per_device(params, xm_local):
        # params leaves: [1, ...] (my stage); xm_local: [M, mb, ...]
        my = jax.tree.map(lambda a: a[0], params)
        m = xm_local.shape[0]
        if schedule == "interleaved":
            outputs, aux_sum = _interleaved_ticks(
                fn, my, xm_local, n_stages, virtual_stages
            )
        elif schedule == "1f1b":
            outputs, aux_sum = _make_1f1b(fn, n_stages)(my, xm_local)
        else:
            outputs, aux_sum = _gpipe_ticks(fn, my, xm_local, n_stages)
        # outputs accumulated on the last stage only (zeros elsewhere):
        # psum replicates the final result across the pipe axis
        out = jax.lax.psum(outputs, MeshAxes.PIPELINE)
        # aux: sum over stages (≡ the unpipelined per-layer sum), averaged
        # over microbatches and over the batch/seq shards each aux saw
        aux = jax.lax.psum(aux_sum, MeshAxes.PIPELINE) / m
        norm_axes = tuple(a for a in (*batch_axes, seq_ax) if a)
        if norm_axes:
            aux = jax.lax.pmean(aux, norm_axes)
        return out, aux

    out, aux = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=(xspec, P()),
        check_vma=False,
    )(stacked_params, xm)
    out = out.reshape(batch, *x.shape[1:])
    return (out, aux) if with_aux else out


def stack_stage_params(param_list) -> Any:
    """Stack per-stage parameter pytrees into the leading-``P`` layout
    ``pipeline_apply`` consumes."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_list)


def stack_chunk_params(param_list, n_stages: int) -> Any:
    """Stack V*P per-chunk parameter pytrees (chunk order: the order the
    microbatch traverses them) into the ``[P, V, ...]`` interleaved
    layout: ``out[p, v]`` is chunk ``v*P + p`` — rank p's v-th virtual
    stage."""
    total = len(param_list)
    if n_stages < 1 or total % n_stages:
        raise InvalidExperimentConfig(
            f"{total} pipeline chunks do not divide over {n_stages} stages"
        )
    v_stages = total // n_stages
    return jax.tree.map(
        lambda *leaves: jnp.stack(
            [
                jnp.stack([leaves[v * n_stages + p] for v in range(v_stages)])
                for p in range(n_stages)
            ]
        ),
        *param_list,
    )
