"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pipe``
mesh axis.

The reference's deepest pipeline support is a DeepSpeed passthrough
(``deepspeed/_mpu.py`` — topology bookkeeping, engine owned by DeepSpeed);
this is the TPU-native schedule itself.  Design (the SPMD pipelining
pattern from the scaling playbook): stage parameters are STACKED on a
leading ``[P, ...]`` dim sharded over ``pipe``; the whole schedule is one
``lax.scan`` inside ``shard_map``, where every tick each device applies
ITS stage to its current activation and hands the result to the next stage
with a single ``ppermute`` rotation.  M microbatches drain in M + P - 1
ticks (the GPipe bubble); reverse-mode AD differentiates straight through
the scan + ppermute (its transpose is the reverse rotation), so the same
function trains.

Composition — the pipe axis composes with every other mesh axis (the
"one mesh subsumes the zoo" design claim, SURVEY §7):
- **data/fsdp**: microbatch rows stay sharded over the batch axes inside
  the schedule (specs below partition both pipe and batch);
- **seq**: the sequence dim of activations stays sharded over the seq
  axis; ring attention runs INSIDE each stage's blocks (the ring is over
  seq shards, orthogonal to the stage rotation over pipe) — see
  ``models/transformer.py`` ``seq_axis_name``;
- **expert**: MoE expert weights are sharded over the expert axis WITHIN
  each stage (``expert_leaf_paths``), and the expert combine is a psum
  over the expert axis inside the stage — the all-to-all never crosses a
  stage boundary.  The reference's DeepSpeed grid composes PP only with
  DP/TP (``deepspeed/_mpu.py:9-50``); seq and expert composition is net-new.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_tpu.parallel.mesh import MeshAxes

# MoE expert-weight param names: leading dim (after the stage stack) is the
# expert dim, shardable over the expert mesh axis.
_EXPERT_PARAM_NAMES = frozenset({"w_in", "w_gate", "w_out"})


def _path_has_expert_leaf(path) -> bool:
    keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
    return any(k == "moe" for k in keys) and keys[-1] in _EXPERT_PARAM_NAMES


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], Any],
    stacked_params: Any,
    x: jax.Array,
    mesh,
    num_microbatches: int,
    with_aux: bool = False,
) -> Any:
    """Run ``stage_fn`` across the mesh's ``pipe`` stages.

    - ``stacked_params``: pytree whose leaves have leading dim P (one slice
      per stage), placed with the leading dim sharded over ``pipe``;
      MoE expert-weight leaves (``.../moe/w_*``) are additionally sharded
      over the expert axis on their dim 1;
    - ``x``: ``[batch, ...]`` global input; batch must divide into
      ``num_microbatches``; when the mesh has a seq axis, dim 1 of ``x``
      is the (sharded) sequence dim;
    - ``with_aux``: ``stage_fn`` returns ``(y, aux_scalar)``; the schedule
      accumulates aux over VALID ticks only (warm-up/drain garbage is
      gated out) and returns ``(out, aux)`` with aux averaged over
      microbatches and summed over stages — matching the unpipelined
      per-layer aux sum;
    - returns ``[batch, ...]`` outputs (plus aux), as if the stages were
      applied sequentially to each microbatch.
    """
    n_stages = mesh.shape.get(MeshAxes.PIPELINE, 1)
    if n_stages == 1:
        params0 = jax.tree.map(lambda a: a[0], stacked_params)
        return stage_fn(params0, x)

    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {num_microbatches} microbatches"
        )
    mb = batch // num_microbatches
    xm = x.reshape(num_microbatches, mb, *x.shape[1:])
    bshards = 1
    for a in (MeshAxes.DATA, MeshAxes.FSDP):
        bshards *= mesh.shape.get(a, 1)

    from determined_tpu.parallel._compat import shard_map

    expert_ax = (
        MeshAxes.EXPERT if mesh.shape.get(MeshAxes.EXPERT, 1) > 1 else None
    )

    def leaf_spec(path, leaf):
        if expert_ax is not None and _path_has_expert_leaf(path):
            return P(MeshAxes.PIPELINE, expert_ax)
        return P(MeshAxes.PIPELINE)

    pspec = jax.tree_util.tree_map_with_path(leaf_spec, stacked_params)
    # microbatch rows shard over the batch axes present in the mesh, so
    # data/fsdp parallelism composes through the pipeline instead of being
    # silently all-gathered away by a replicated in_spec; microbatches too
    # small to split fall back to replication (still correct, no speedup)
    batch_axes = tuple(
        a for a in (MeshAxes.DATA, MeshAxes.FSDP) if mesh.shape.get(a, 1) > 1
    )
    if mb % bshards:
        batch_axes = ()
    # seq axis: dim 1 of the original x (dim 2 of xm) stays sharded — ring
    # attention inside the stage works on the local shard
    seq_ax = (
        MeshAxes.SEQUENCE
        if (x.ndim >= 2 and mesh.shape.get(MeshAxes.SEQUENCE, 1) > 1)
        else None
    )
    xspec = P(None, batch_axes or None, seq_ax, *([None] * (x.ndim - 2)))

    fn = stage_fn if with_aux else (lambda p, h: (stage_fn(p, h), jnp.zeros((), jnp.float32)))

    def per_device(params, xm_local):
        # params leaves: [1, ...] (my stage); xm_local: [M, mb, ...]
        my = jax.tree.map(lambda a: a[0], params)
        p = jax.lax.axis_index(MeshAxes.PIPELINE)
        n = n_stages
        m = xm_local.shape[0]
        ticks = m + n - 1

        zero = jnp.zeros_like(xm_local[0])
        outputs = jnp.zeros_like(xm_local)
        aux0 = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            state_in, outs, aux_sum = carry
            # stage 0 ingests microbatch t while it exists; later stages
            # consume the rotated activation from the previous tick
            fresh = jax.lax.dynamic_index_in_dim(
                xm_local, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            use_fresh = jnp.logical_and(p == 0, t < m)
            x_in = jnp.where(use_fresh, fresh, state_in)
            y, aux = fn(my, x_in)
            # stage p processes microbatch t - p at tick t; outside [0, m)
            # the input is warm-up/drain garbage — gate its aux out
            mb_idx = t - p
            work_valid = jnp.logical_and(mb_idx >= 0, mb_idx < m)
            aux_sum = aux_sum + jnp.where(work_valid, aux, 0.0)
            # last stage emits microbatch t - (n - 1)
            out_idx = t - (n - 1)
            prev = jax.lax.dynamic_index_in_dim(
                outs, jnp.clip(out_idx, 0, m - 1), 0, keepdims=False
            )
            valid = jnp.logical_and(
                p == n - 1, jnp.logical_and(out_idx >= 0, out_idx < m)
            )
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, prev), jnp.clip(out_idx, 0, m - 1), 0
            )
            # rotate activations one stage forward
            state_out = jax.lax.ppermute(
                y, MeshAxes.PIPELINE, [(i, (i + 1) % n) for i in range(n)]
            )
            return (state_out, outs, aux_sum), None

        (_, outputs, aux_sum), _ = jax.lax.scan(
            tick, (zero, outputs, aux0), jnp.arange(ticks)
        )
        # outputs accumulated on the last stage only (zeros elsewhere):
        # psum replicates the final result across the pipe axis
        out = jax.lax.psum(outputs, MeshAxes.PIPELINE)
        # aux: sum over stages (≡ the unpipelined per-layer sum), averaged
        # over microbatches and over the batch/seq shards each aux saw
        aux = jax.lax.psum(aux_sum, MeshAxes.PIPELINE) / m
        norm_axes = tuple(a for a in (*batch_axes, seq_ax) if a)
        if norm_axes:
            aux = jax.lax.pmean(aux, norm_axes)
        return out, aux

    out, aux = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=(xspec, P()),
        check_vma=False,
    )(stacked_params, xm)
    out = out.reshape(batch, *x.shape[1:])
    return (out, aux) if with_aux else out


def stack_stage_params(param_list) -> Any:
    """Stack per-stage parameter pytrees into the leading-``P`` layout
    ``pipeline_apply`` consumes."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_list)
