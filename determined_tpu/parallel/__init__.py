from determined_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    MeshAxes,
    make_mesh,
    make_virtual_mesh,
    local_mesh_devices,
)
from determined_tpu.parallel.sharding import (  # noqa: F401
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_to_mesh_spec,
    shard_params,
    named_sharding,
    with_sharding_constraint,
    batch_sharding,
)
