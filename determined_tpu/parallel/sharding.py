"""Logical-axis sharding rules: how arrays map onto the mesh.

Model code annotates parameters and activations with *logical* axis names
("embed", "mlp", "heads", "batch", "length", ...).  A ``LogicalAxisRules``
table maps logical names to mesh axes.  Swapping the table reconfigures a
model between DP / FSDP / TP / SP without touching model code — the TPU
answer to the reference's per-launcher wrapping (``wrap_model`` DDP at
``_pytorch_context.py:36-...``, DeepSpeed engine wrap, Horovod broadcast).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from determined_tpu.parallel.mesh import MeshAxes

# A logical spec is a tuple of logical axis names (or None), one per dim.
LogicalSpec = Tuple[Optional[str], ...]

# Rules: logical axis name -> mesh axis (str), tuple of mesh axes, or None.
LogicalAxisRules = Dict[str, Union[str, Tuple[str, ...], None]]

# Default rules: batch over (data, fsdp); params sharded over fsdp on their
# largest dim; tensor-parallel on heads/mlp; sequence activations over seq.
DEFAULT_RULES: LogicalAxisRules = {
    "batch": (MeshAxes.DCN, MeshAxes.DATA, MeshAxes.FSDP),
    "length": MeshAxes.SEQUENCE,
    "embed": None,
    "mlp": MeshAxes.TENSOR,
    "heads": MeshAxes.TENSOR,
    "kv": None,
    "head_dim": None,
    "vocab": MeshAxes.TENSOR,
    "expert": MeshAxes.EXPERT,
    "stage": MeshAxes.PIPELINE,
    # FSDP: weight dims tagged "fsdp_shard" get scattered over the fsdp axis.
    "fsdp_shard": MeshAxes.FSDP,
}


def logical_to_mesh_spec(
    logical: Optional[LogicalSpec],
    rules: LogicalAxisRules,
    mesh: Optional[Mesh] = None,
) -> P:
    """Translate a logical spec into a ``PartitionSpec``.

    Mesh axes that do not exist in ``mesh`` (size-1 or absent) are dropped,
    so the same model + rules run unchanged on any topology.
    """
    if logical is None:
        return P()
    # mesh.shape works for both concrete Mesh and AbstractMesh
    axis_sizes = dict(mesh.shape) if mesh is not None else None

    def resolve(name: Optional[str]):
        if name is None:
            return None
        target = rules.get(name, None)
        if target is None:
            return None
        targets = target if isinstance(target, tuple) else (target,)
        if axis_sizes is not None:
            targets = tuple(t for t in targets if axis_sizes.get(t, 1) > 1)
        if not targets:
            return None
        return targets if len(targets) > 1 else targets[0]

    resolved = [resolve(n) for n in logical]
    # PartitionSpec forbids repeating a mesh axis; keep first occurrence.
    seen = set()
    out = []
    for r in resolved:
        flat = r if isinstance(r, tuple) else (r,) if r else ()
        if any(f in seen for f in flat):
            out.append(None)
            continue
        seen.update(flat)
        out.append(r)
    return P(*out)


def named_sharding(
    mesh: Mesh, logical: Optional[LogicalSpec], rules: Optional[LogicalAxisRules] = None
) -> NamedSharding:
    rules = rules if rules is not None else DEFAULT_RULES
    return NamedSharding(mesh, logical_to_mesh_spec(logical, rules, mesh))


# In spec pytrees the LEAVES are logical specs: tuples of names (or None, or
# a PartitionSpec).  Without this is_leaf, tree.map would descend into the
# tuples and iterate the axis-name strings character by character.
def _is_spec_leaf(x: Any) -> bool:
    return (
        x is None
        or isinstance(x, P)
        or (isinstance(x, tuple) and all(n is None or isinstance(n, (str, tuple)) for n in x))
    )


def shard_params(params: Any, specs: Any, mesh: Mesh, rules: Optional[LogicalAxisRules] = None) -> Any:
    """Device-put a param pytree according to its logical-spec pytree."""
    rules = rules if rules is not None else DEFAULT_RULES
    return jax.tree.map(
        lambda p, s: jax.device_put(p, named_sharding(mesh, s, rules)),
        params,
        specs,
        is_leaf=_is_spec_leaf,
    )


def param_shardings(specs: Any, mesh: Mesh, rules: Optional[LogicalAxisRules] = None) -> Any:
    """NamedSharding pytree matching a logical-spec pytree (for jit in/out)."""
    rules = rules if rules is not None else DEFAULT_RULES
    return jax.tree.map(
        lambda s: named_sharding(mesh, s, rules), specs, is_leaf=_is_spec_leaf
    )


def with_sharding_constraint(
    x: Any, logical: LogicalSpec, mesh: Optional[Mesh] = None, rules: Optional[LogicalAxisRules] = None
) -> Any:
    """Annotate an activation with a logical sharding inside jit."""
    rules = rules if rules is not None else DEFAULT_RULES
    try:
        if mesh is None:
            mesh = _current_mesh()
        if mesh is None:
            return x
        spec = logical_to_mesh_spec(logical, rules, mesh)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except (ValueError, RuntimeError):
        return x


def _current_mesh() -> Optional[Mesh]:
    try:
        from jax._src.mesh import thread_resources

        env_mesh = thread_resources.env.physical_mesh
        if env_mesh and not env_mesh.empty:
            return env_mesh
    except Exception:
        pass
    if hasattr(jax.sharding, "get_abstract_mesh"):
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is not None and getattr(mesh, "axis_names", ()):
            return mesh
    return None


def batch_sharding(mesh: Mesh, rules: Optional[LogicalAxisRules] = None, extra_dims: int = 1) -> NamedSharding:
    """Sharding for an input batch: ('batch', None, ...)."""
    logical: LogicalSpec = ("batch",) + (None,) * extra_dims
    return named_sharding(mesh, logical, rules)


def grad_sync_spec(
    shape: Sequence[int], param_spec: P, mesh: Mesh, sync_axes: Sequence[str]
) -> Optional[P]:
    """PartitionSpec for a gradient leaf synced by reduce-scatter.

    The overlapped gradient sync (``train/_overlap.py``) wants each grad
    leaf SHARDED over the gradient-reduction axes (data x fsdp) instead of
    replicated-after-all-reduce: XLA then lowers the reduction to a
    reduce-scatter at the grad's production point, the optimizer update
    runs on 1/n of the elements per device (ZeRO-style), and the updated
    params all-gather back to ``param_spec``.

    Dim choice: prefer a dim already carrying one of ``sync_axes`` in the
    param's own spec (the fsdp-sharded dim — extending it avoids a
    resharding hop), else the largest dim with no existing assignment.
    The chosen dim's total shard count must divide its size; a leaf with
    no such dim returns None (it rides the default all-reduce).
    """
    entries = list(param_spec) if param_spec is not None else []
    entries += [None] * (len(shape) - len(entries))
    norm = [
        tuple(e) if isinstance(e, (tuple, list)) else ((e,) if e else ())
        for e in entries
    ]
    used = {a for e in norm for a in e}
    missing = [a for a in sync_axes if a not in used]
    if not missing:
        return None  # already fully sharded over the sync axes
    missing_n = 1
    for a in missing:
        missing_n *= mesh.shape.get(a, 1)
    if missing_n <= 1:
        return None

    def dim_ok(d: int, extra: int) -> bool:
        have = 1
        for a in norm[d]:
            have *= mesh.shape.get(a, 1)
        return shape[d] >= have * extra and shape[d] % (have * extra) == 0

    # a dim already sharded over one of the sync axes, then largest free dim
    carrier = None
    for d in range(len(shape)):
        if any(a in sync_axes for a in norm[d]) and dim_ok(d, missing_n):
            carrier = d
            break
    if carrier is None:
        free = [d for d in range(len(shape)) if not norm[d] and dim_ok(d, missing_n)]
        if not free:
            return None
        carrier = max(free, key=lambda d: shape[d])
    out = list(norm)
    out[carrier] = out[carrier] + tuple(missing)
    return P(*[e if len(e) > 1 else (e[0] if e else None) for e in out])
