"""jax version-compatibility shims for the parallel/ops layers.

The shard_map API has moved twice across the jax versions this repo must
run on: the entry point migrated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (>= 0.6), and the replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` when varying-manual-axes tracking replaced
the old rep-set analysis.  Callers here write the NEW spelling
(``check_vma``) and this shim translates for older installs, so kernel
code stays forward-looking without pinning jax.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

try:  # jax >= 0.6 moved shard_map to the top-level namespace
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def axis_size(axis_name: Any) -> int:
    """Static size of a manual-collective axis.

    ``jax.lax.axis_size`` only exists on newer jax; under older versions
    ``psum`` of a literal 1 constant-folds to the same static size.
    """
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(
    f: Any,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: Any = None,
) -> Any:
    """``jax.shard_map`` with the modern kwarg spelling on any jax version."""
    kwargs = {}
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
