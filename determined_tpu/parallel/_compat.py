"""jax version-compatibility shims for the parallel/ops layers.

The shard_map API has moved twice across the jax versions this repo must
run on: the entry point migrated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (>= 0.6), and the replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` when varying-manual-axes tracking replaced
the old rep-set analysis.  Callers here write the NEW spelling
(``check_vma``) and this shim translates for older installs, so kernel
code stays forward-looking without pinning jax.

It also backports the shard_map TRANSPOSE fix (``_fix_transpose_residual_
misalignment`` below): jax 0.4.37's ``_shard_map_transpose`` zips the
backward pass's outputs — which lead with the RESIDUAL cotangents of the
partial-evaluated forward — directly against ``in_names``, so whenever
partial eval hoists residual-producing computation the names misalign and
the pipeline × expert/seq compositions die in ``_check_names`` with a
``_SpecError`` on a scalar cotangent.  Later jax slices the residual
cotangents off and re-merges explicit Zeros (jax-ml/jax: shard_map
transpose residual fix); we install exactly that corrected rule when the
buggy pattern is detected in the installed version.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

try:  # jax >= 0.6 moved shard_map to the top-level namespace
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def _fix_transpose_residual_misalignment() -> bool:
    """Re-register a corrected shard_map transpose on affected jax.

    Returns True when the fix was installed (new jax either lacks the bug
    or moved the internals, in which case this is a silent no-op — the
    feature test is the buggy source pattern itself, not a version pin).
    """
    try:
        import jax.experimental.shard_map as _sm
        from jax._src import ad_util, dtypes
        from jax._src import linear_util as lu
        from jax._src.api_util import flatten_fun_nokwargs
        from jax._src.interpreters import ad, partial_eval as pe
        from jax._src.util import merge_lists, partition_list
        from jax._src import core as jcore
        from jax.tree_util import tree_flatten, tree_unflatten

        buggy = "zip(in_names, out)" in inspect.getsource(_sm._shard_map_transpose)
    except Exception:  # noqa: BLE001 - internals moved; nothing to patch
        return False
    if not buggy:
        return False

    from math import prod

    _shard_aval = _sm._shard_aval
    _unshard_aval = _sm._unshard_aval
    _unmentioned2 = _sm._unmentioned2

    def _fixed_transpose(out_cts, *args, jaxpr, mesh, in_names, out_names,
                         check_rep, rewrite, auto):
        mb_div = lambda x, y: x / y if y != 1 else x  # noqa: E731
        out_cts = [
            ad.Zero(_shard_aval(mesh, ns, x.aval)) if type(x) is ad.Zero
            else x if rewrite or dtypes.dtype(x) == dtypes.float0
            else mb_div(x, prod(map(mesh.shape.get, _unmentioned2(mesh, ns, auto))))
            for ns, x in zip(out_names, out_cts)
        ]
        args = [x if type(x) is not ad.UndefinedPrimal else
                ad.UndefinedPrimal(_shard_aval(mesh, ns, x.aval))
                for ns, x in zip(in_names, args)]
        all_args, in_tree = tree_flatten((out_cts, args))

        @lu.wrap_init
        def fun_trans(out_cts, args):
            in_undef = list(map(ad.is_undefined_primal, args))
            res, undefs = partition_list(in_undef, args)
            jaxpr_known, jaxpr_unknown, _, _ = pe.partial_eval_jaxpr_nounits(
                pe.close_jaxpr(jaxpr), in_undef, False)
            res_reshaped = jcore.jaxpr_as_fun(jaxpr_known)(*res)
            # the first len(res_reshaped) cotangents belong to the hoisted
            # residuals, NOT to the original inputs: slice them off before
            # pairing with in_names (the 0.4.37 bug is skipping this)
            in_cts = ad.backward_pass(
                jaxpr_unknown.jaxpr, False, (), (*res_reshaped, *undefs), out_cts
            )[len(res_reshaped):]
            _, in_ct_names = partition_list(in_undef, in_names)
            in_cts = [
                ad.Zero(_unshard_aval(mesh, ns, x.aval)) if type(x) is ad.Zero
                else x if rewrite
                else jax.lax.psum(x, tuple(_unmentioned2(mesh, ns, auto)))
                for ns, x in zip(in_ct_names, in_cts)
            ]
            res_zeros = [ad_util.zero_from_primal(r) for r in res]
            return merge_lists(in_undef, res_zeros, in_cts)

        fun_trans, nz_arg_cts = ad.nonzero_outputs(fun_trans)
        fun_trans_flat, out_tree = flatten_fun_nokwargs(fun_trans, in_tree)

        new_in_names = (
            [n for n, x in zip(out_names, out_cts) if type(x) is not ad.Zero]
            + [n for n, x in zip(in_names, args)
               if type(x) is not ad.UndefinedPrimal]
        )

        def new_out_names_thunk():
            return tuple(names for names, nz in zip(in_names, nz_arg_cts()) if nz)

        out_flat = _sm.shard_map_p.bind(
            fun_trans_flat, *all_args, mesh=mesh, in_names=tuple(new_in_names),
            out_names_thunk=new_out_names_thunk, check_rep=check_rep,
            rewrite=rewrite, auto=auto)
        return tree_unflatten(out_tree(), out_flat)

    ad.primitive_transposes[_sm.shard_map_p] = _fixed_transpose
    return True


SHARD_MAP_TRANSPOSE_FIXED = _fix_transpose_residual_misalignment()

_sharded_restack_safe: Any = None


def sharded_restack_safe() -> bool:
    """Feature probe: does stack-into-sharded-output preserve values?

    On jax 0.4.37's forced-host CPU platform, a jitted program that
    ``jnp.stack``s (concatenates) replicated operands into an output whose
    ``out_shardings`` shard it over a MULTI-axis mesh returns the values
    multiplied by the size of the unused mesh axes — the SPMD partitioner
    treats the replicated concatenate operands as partial sums and inserts
    a reduction over the axes the output is replicated on.  Measured: a
    ``stack([ones, ones*3])`` sharded over ``pipe`` on a pipe2 x data2
    mesh returns ``[2., 6.]``; any single-non-trivial-axis mesh, an
    identity reshard, or constants baked into the trace are all correct.

    This is exactly the pipeline param-restack shape: ``Trainer._setup``
    initializes the stacked stage blocks sharded over ``pipe``, so pipe>1
    trials used to start from DOUBLED block weights relative to the
    pipe=1 comparator — the whole ~1.5% pipe-parity drift ROADMAP
    tracked.  When this probe reports unsafe, the Trainer stages init:
    the RNG-bearing phase materializes fully replicated (correct values),
    the restack runs eagerly, and the reshard goes through
    ``jax.device_put`` (both measured safe).  The probe is the observed
    behavior itself, not a version pin, and is cached for the process.
    """
    global _sharded_restack_safe
    if _sharded_restack_safe is not None:
        return _sharded_restack_safe
    devs = jax.devices()
    if len(devs) < 4:
        # the corruption needs >= 2 non-trivial mesh axes (measured: any
        # single-axis or size-1-padded mesh is correct), which takes at
        # least 4 devices — fewer devices cannot hit it
        _sharded_restack_safe = True
        return True
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(
        np.asarray(devs[:4], dtype=object).reshape(2, 2), ("_rsk_a", "_rsk_b")
    )
    x = jnp.ones((4, 8), jnp.float32)
    got = jax.jit(
        lambda a: jnp.stack([a, a]),
        out_shardings=NamedSharding(mesh, PartitionSpec("_rsk_a")),
    )(x)
    _sharded_restack_safe = bool(np.asarray(got).max() == 1.0)
    return _sharded_restack_safe


def axis_size(axis_name: Any) -> int:
    """Static size of a manual-collective axis.

    ``jax.lax.axis_size`` only exists on newer jax; under older versions
    ``psum`` of a literal 1 constant-folds to the same static size.
    """
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(
    f: Any,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: Any = None,
) -> Any:
    """``jax.shard_map`` with the modern kwarg spelling on any jax version."""
    kwargs = {}
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
